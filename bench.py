"""Benchmark runner — prints ONE JSON line for the driver.

Primary metric: GPT (125M-class) training throughput in tokens/sec/chip —
fused fwd+bwd+AdamW in one jitted executable, bf16 compute with fp32
master params (the BASELINE GPT workload scaled to one chip).  The
``extra.configs`` map carries the other BASELINE workloads measured on the
same chip: GPT-350M (larger single-chip config so the headline MFU is not
a 125M proxy), ResNet-50 images/sec, and BERT-base AMP tokens/sec.

MFU accounting: model FLOPs per token = 6·N_params (fwd 2N + bwd 4N; the
tied LM head matmul is covered by counting the embedding table once, the
input lookup is gather-only) + 6·L·S·H for causal attention scores/values
(QKᵀ and AV are real executed matmuls; the causal flash kernel computes
half the S² square, hence 6 not 12 per layer-token).  Dividing by the
chip's peak bf16 FLOPs gives MFU.

Timing: through the axon PJRT tunnel block_until_ready() returns BEFORE
execution finishes (~70x inflation) — every loop ends with a host
readback (float of a value data-dependent on the whole step chain), which
is a true completion barrier.  tests/test_bench_timing.py guards this.

Dropout note: all benched models run with dropout probability 0.0 (the
perf-relevant configs train without dropout); nets are put in eval() mode
purely so no dropout mask ops enter the graph — the math equals train()
at p=0.
"""
import json
import os
import time

import numpy as np


def _readback_sync(x):
    """True device-completion barrier: D2H of a dependent value."""
    return float(x)


def _dispatch_latency_ms():
    """Median round-trip of a tiny jitted reduction — the per-dispatch
    tunnel latency the validity gates subtract/compare against.  NOT
    ``chip_calibration``: its 300-matmul compute chain is for peak-frac,
    overkill here and pathological on the CPU proxy.  Returns None when
    the probe itself fails (callers then report validity as unknown)."""
    try:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _tiny(a):
            return jnp.sum(a)
        x = jnp.zeros((8, 8), jnp.float32)
        _readback_sync(_tiny(x))
        lats = []
        for _ in range(3):
            t0 = time.perf_counter()
            _readback_sync(_tiny(x))
            lats.append(time.perf_counter() - t0)
        return sorted(lats)[1] * 1e3
    except Exception:
        return None


def _telemetry_snapshot(tag, reset=True):
    """Dump the observability registry as sink-format fixtures next to
    the bench JSON: ``<dir>/<tag>.prom`` (Prometheus text exposition) +
    ``<tag>.jsonl`` (the PADDLE_METRICS_LOG line format), dir from
    ``BENCH_TELEMETRY_DIR`` (default ``telemetry/``).  ``reset`` zeroes
    the registry afterwards so the next config's snapshot is its own
    (counters are process-cumulative otherwise).

    Idempotent per tag: the ``.prom`` write truncates (atomic replace)
    and the ``.jsonl`` write is run-id-keyed (``replace_run``), so
    re-running bench updates the snapshot in place instead of appending
    one copy per invocation.  A run that produced request-trace spans
    (serving configs) also drops ``<tag>_requests.trace.json`` — the
    per-request-lane chrome trace ``report --requests`` summarizes."""
    try:
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import export as obs_export
        from paddle_tpu.observability import timeline as obs_timeline
        from paddle_tpu.observability import tracing as obs_tracing
        d = os.environ.get("BENCH_TELEMETRY_DIR", "telemetry")
        os.makedirs(d, exist_ok=True)
        prom = obs_export.write_prometheus(os.path.join(d, f"{tag}.prom"))
        jsl = obs_export.write_jsonl(os.path.join(d, f"{tag}.jsonl"),
                                     run=tag, replace_run=True)
        out = {"prometheus": prom, "jsonl": jsl}
        if obs_tracing.spans():
            out["requests_trace"] = obs_timeline.export_chrome_trace(
                os.path.join(d, f"{tag}_requests.trace.json"),
                include_profiler=False, include_guardian=False,
                include_samples=False)
            obs_tracing.reset()
        if reset:
            obs.get_registry().reset()
        return out
    except Exception as e:  # telemetry must never sink the bench line
        return {"error": repr(e)[:160]}


def _roofline_snapshot(measured_ms, peak_flops, hbm_bw):
    """Join the process's compile telemetry (every surface any config
    compiled) with measured step latency into the per-surface
    roofline/MFU-attribution table the MFU-plateau roadmap item asks
    for, committed as ``<dir>/roofline.json`` (the same table
    ``report --roofline`` renders from a ``.prom`` snapshot)."""
    try:
        import json as _json
        from paddle_tpu.observability import compilestats, report
        stats = compilestats.snapshot()
        if not stats:
            return {"skipped": "no compile telemetry recorded"}
        table = report.roofline_from_stats(stats, measured_ms,
                                           peak_flops, hbm_bw)
        d = os.environ.get("BENCH_TELEMETRY_DIR", "telemetry")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "roofline.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            _json.dump(table, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return {"roofline": path, "surfaces": len(table["rows"])}
    except Exception as e:
        return {"error": repr(e)[:160]}


def _memory_snapshot():
    """Write the HBM ledger's two-sided snapshot next to roofline.json
    (``<dir>/memory.json``): one static memory_analysis row per
    registry surface plus the run's census/forecast summary.  The
    bench gate requires this artifact to accompany committed BENCH_*
    files — a quant/serving change must never land without its memory
    story."""
    try:
        from paddle_tpu.observability import memory
        path = memory.write_memory_json()
        snap = memory.snapshot()
        compiled = sum(1 for r in snap["surfaces"].values()
                       if r.get("compiled"))
        return {"memory": path, "surfaces": len(snap["surfaces"]),
                "compiled": compiled}
    except Exception as e:
        return {"error": repr(e)[:160]}


def _timeit(step, iters, *state):
    """Run ``state = step(*state)`` iters times; the caller's step returns
    (loss_like_scalar, *new_state).  Returns (seconds, final_loss)."""
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        out = step(*state)
        loss, state = out[0], out[1:]
    final = _readback_sync(loss)
    dt = time.perf_counter() - t0
    return dt, final, state


def chip_calibration():
    """Tunnel health probe: (dispatch_latency_ms, matmul_peak_frac).

    The axon tunnel's per-call dispatch latency varies from ~5ms
    (healthy) to ~100ms (congested, observed for hours in round 4);
    short-step benches (eager overhead, fp8 micro ratios, S<=4096
    steps) degrade with it while long fused steps are barely touched —
    sustained compute stayed at full speed even during congestion.
    Latency is measured on a trivial op and SUBTRACTED from the matmul
    chain so peak_frac reflects actual compute health.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(4096, 4096).astype("f4"), dtype=jnp.bfloat16)
    b = jnp.asarray(rng.randn(4096, 4096).astype("f4"), dtype=jnp.bfloat16)

    @jax.jit
    def tiny(a):
        return jnp.sum(a[:8, :8].astype(jnp.float32))

    # chain length must make COMPUTE dominate the dispatch latency, or
    # the subtraction bottoms out and the frac reads nonsense (a 20-matmul
    # chain is ~14ms — under one 90ms congested-tunnel round trip).
    # 300 matmuls ~ 0.2s at peak: latency-robust within ~5%.
    N_CHAIN = 300

    @jax.jit
    def chain(a, b):
        def body(_, o):
            return (o @ b).astype(jnp.bfloat16)
        o = jax.lax.fori_loop(0, N_CHAIN, body, a)
        return jnp.sum(o.astype(jnp.float32))

    import statistics

    # MEDIAN of N for BOTH sides of the subtraction (BENCH_r05 fix):
    # min(tiny) - min(chain) paired the luckiest dispatch against the
    # luckiest chain run, so whenever tunnel jitter exceeded the ~5%
    # margin the subtraction overcorrected and the raw frac read >1.0
    # (1.198 in r05, tripping jitter_suspect on every run).  Medians of
    # the same sample counts are robust to one congested round trip in
    # either direction; min latency is still reported separately (it IS
    # the best-case dispatch floor the serving engine amortizes).
    _readback_sync(tiny(a))
    tiny_times = []
    for _ in range(7):
        t0 = time.perf_counter()
        _readback_sync(tiny(a))
        tiny_times.append(time.perf_counter() - t0)
    lat = statistics.median(tiny_times)
    _readback_sync(chain(a, b))
    chain_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        _readback_sync(chain(a, b))
        chain_times.append(time.perf_counter() - t0)
    med = statistics.median(chain_times)
    per = max(med - lat, 1e-6) / N_CHAIN
    frac = 2 * 4096 ** 3 / per / 197e12
    # frac above 1.0 is physically impossible — it means the dispatch
    # latency measured on the tiny probe overshot the latency actually
    # paid by the chain run (jitter between the two measurements), and
    # the subtraction overcorrected.  With the median-of-N subtraction
    # above that now genuinely signals something pathological (clock
    # skew, a wrong peak constant), not routine tunnel noise.  Clamp
    # the headline number so downstream health checks can treat it as a
    # fraction, keep the raw value for trend analysis, and flag the
    # jitter machine-readably instead of in a free-text note.
    out = {"dispatch_latency_ms": round(min(tiny_times) * 1e3, 1),
           "dispatch_latency_median_ms": round(lat * 1e3, 1),
           "matmul_peak_frac": round(min(frac, 1.0), 4),
           "matmul_peak_frac_raw": round(frac, 4),
           "jitter_suspect": frac > 1.0}
    return out


# ---------------------------------------------------------------------------
# GPT (125M / 350M): fused fwd+bwd+AdamW, bf16 compute fp32 master
# ---------------------------------------------------------------------------

def bench_gpt(cfg, B, S, iters, peak):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.models import GPTForPretraining

    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()  # dropout-mask-free graph; p=0.0 so math == train()
    params = [p for _, p in net.named_parameters()]
    pvals = [p._value for p in params]

    def forward_pure(pv, ids):
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                return net(paddle.Tensor(ids))._value
        finally:
            for p, v in zip(params, olds):
                p._value = v

    def loss_fn(pv, ids, labels):
        # Pallas fused softmax-xent: ONE streamed pass fwd (online
        # max/sum + label pick, no slicing copy — the shift rides an
        # ignore label), ONE pass bwd writing dlogits directly.  42.3%
        # MFU with the jnp LSE loss -> 46.4% with this kernel (B=24).
        from paddle_tpu.ops.pallas.fused_xent import fused_softmax_xent
        compute = [v.astype(jnp.bfloat16)
                   if jnp.issubdtype(v.dtype, jnp.floating) else v
                   for v in pv]
        logits = forward_pure(compute, ids)              # bf16 [B,S,V]
        Bv, Sv, V = logits.shape
        lb = jnp.concatenate([labels[:, 1:],
                              jnp.full((Bv, 1), -1, labels.dtype)], 1)
        row = fused_softmax_xent(logits.reshape(Bv * Sv, V),
                                 lb.reshape(-1).astype(jnp.int32))
        return jnp.sum(row) / (Bv * (Sv - 1))

    b1, b2, eps, lr, wd = 0.9, 0.95, 1e-8, 1e-4, 0.01

    def step(pv, m, v, t, ids, labels):
        loss, g = jax.value_and_grad(loss_fn)(pv, ids, labels)
        t = t + 1
        new_p, new_m, new_v = [], [], []
        for p, gi, mi, vi in zip(pv, g, m, v):
            nmi = b1 * mi + (1 - b1) * gi
            nvi = b2 * vi + (1 - b2) * gi * gi
            mhat = nmi / (1 - b1 ** t)
            vhat = nvi / (1 - b2 ** t)
            np_ = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
            new_p.append(np_)
            new_m.append(nmi)
            new_v.append(nvi)
        return loss, new_p, new_m, new_v, t

    # K train steps ride ONE dispatch via lax.scan: the axon tunnel's
    # per-call latency was observed anywhere between ~5ms and ~100ms
    # (round 4), which would otherwise contaminate short steps
    K = int(os.environ.get("BENCH_STEPS_PER_CALL", "5"))

    def scan_steps(pv, m, v, t, ids, labels):
        def body(carry, _):
            pv, m, v, t = carry
            loss, pv, m, v, t = step(pv, m, v, t, ids, labels)
            return (pv, m, v, t), loss
        (pv, m, v, t), losses = jax.lax.scan(
            body, (pv, m, v, t), None, length=K)
        return losses[-1], pv, m, v, t

    # compile telemetry (observability/compilestats.py): the scan
    # stepper is ONE executable covering K inner steps — its analytical
    # FLOPs/bytes and the per-DISPATCH latency recorded below are what
    # `report --roofline` / telemetry/roofline.json join
    from paddle_tpu.observability import compilestats as _cstats
    step_jit = _cstats.wrap(jax.jit(scan_steps, donate_argnums=(0, 1, 2)),
                            "bench.train_step", budget=1)
    m0 = [jnp.zeros_like(v) for v in pvals]
    v0 = [jnp.zeros_like(v) for v in pvals]
    t0 = jnp.zeros((), jnp.int32)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (B, S)).astype("int32"))

    def run(pv, m, v, t):
        loss, pv, m, v, t = step_jit(pv, m, v, t, ids, ids)
        return loss, pv, m, v, t

    loss, pvals, m0, v0, t0 = run(pvals, m0, v0, t0)
    _readback_sync(loss)  # compile + warmup
    dt, final_loss, _ = _timeit(run, iters, pvals, m0, v0, t0)
    tokens_per_sec = iters * K * B * S / dt

    # aggregate telemetry for the train-config snapshot: the scan-
    # chained loop deliberately has no per-step sync, so one latency
    # observation = the measured mean step (latency-robust, same number
    # the JSON reports)
    from paddle_tpu import observability as obs
    obs.observe("pt_train_step_latency_ms", dt / (iters * K) * 1e3)
    # per-DISPATCH measured latency for the roofline join (the scan
    # covers K steps, so this is K x the per-step number above)
    obs.observe("pt_compile_dispatch_ms", dt / iters * 1e3,
                surface="bench.train_step")
    obs.inc("pt_train_tokens_total", iters * K * B * S)
    obs.set_gauge("pt_train_tokens_per_sec", tokens_per_sec)
    obs.set_gauge("pt_train_loss", final_loss)

    n_params = sum(int(np.prod(p.shape)) for p in params)
    flops_per_tok = 6 * n_params \
        + 6 * cfg.num_hidden_layers * S * cfg.hidden_size  # causal attn
    mfu = tokens_per_sec * flops_per_tok / peak
    return {"tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4), "loss": round(final_loss, 4),
            "params": n_params, "batch": B, "seq": S,
            "step_ms": round(dt / (iters * K) * 1e3, 3),
            "dispatch_ms": round(dt / iters * 1e3, 3)}


def bench_longctx_sweep(peak, on_tpu=True):
    """remat-policy x attention-impl grid at the long-context shape
    (ISSUE 15): selective remat frees activation HBM so the batch can
    grow past the B=2 operating point the no-remat sweep topped out at,
    and the attention-impl axis isolates how much of each cell is the
    flash kernel vs the dense XLA path.  Opt-in
    (``BENCH_CONFIGS=longctx_sweep``): the grid costs one compile per
    cell.  Off-TPU a tiny proxy runs the same grid through interpret
    mode — plumbing and reporting, not physics."""
    from paddle_tpu.models import GPTConfig
    if on_tpu:
        shape = dict(vocab_size=50304, hidden_size=768,
                     num_hidden_layers=12, num_attention_heads=12,
                     max_position_embeddings=4096)
        S, iters = 4096, 6
        # (remat_policy, attn_impl, B): the no-remat B sweep topped out
        # at B=2 (46.7%); dots_saveable cells probe past it
        combos = [(None, "flash", 2), (None, "dense", 2),
                  ("dots_saveable", "flash", 4),
                  ("dots_saveable", "flash", 8),
                  ("dots_saveable", "dense", 8)]
    else:
        shape = dict(vocab_size=1024, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=2,
                     max_position_embeddings=512)
        S, iters = 512, 2
        combos = [(None, "dense", 2), (None, "flash", 2),
                  ("dots_saveable", "flash", 4)]
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TPU_ATTN_IMPL", "PADDLE_TPU_KERNEL_INTERPRET")}
    rows = []
    try:
        for policy, impl, B in combos:
            os.environ["PADDLE_TPU_ATTN_IMPL"] = \
                "flash" if impl == "flash" else "dense"
            if not on_tpu and impl == "flash":
                os.environ["PADDLE_TPU_KERNEL_INTERPRET"] = "1"
            elif not on_tpu:
                os.environ.pop("PADDLE_TPU_KERNEL_INTERPRET", None)
            row = {"remat_policy": policy, "attn_impl": impl, "batch": B}
            try:
                cfg = GPTConfig(**shape, remat_policy=policy)
                r = bench_gpt(cfg, B=B, S=S, iters=iters, peak=peak)
                row.update(tokens_per_sec=r["tokens_per_sec"],
                           mfu=r["mfu"], step_ms=r["step_ms"])
            except Exception as e:
                row["error"] = repr(e)[:160]
            rows.append(row)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    ok = [r for r in rows if "error" not in r]
    # best stays NESTED (no top-level rate keys): the sweep is opt-in,
    # and a sometimes-present top-level metric would trip the bench
    # gate's disappearance check on runs that skip it
    return {"rows": rows,
            "best": max(ok, key=lambda r: r["mfu"]) if ok else None,
            "seq": S}


def bench_kernel_probe(on_tpu=True):
    """Standalone kernel-surface probe (opt-in ``kernels`` config):
    dispatch the registry-tracked flash + fused-xent kernels outside any
    stepper so compilestats owns ``kernel.*`` rows (analytical
    FLOPs/bytes from the AOT lowering), run the block-size autotune
    micro-sweep, and time each kernel latency-clean — the measured ms
    feed the roofline join, which is how ``telemetry/roofline.json``
    attributes the per-kernel share of the step.  Off-TPU the same
    probe runs tiny shapes through interpret mode (plumbing, labeled
    cpu-proxy by the peak constant — not physics)."""
    import statistics

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import registry as kreg
    from paddle_tpu.nn.functional import attention as fattn
    from paddle_tpu.ops.pallas import fused_xent as fx

    prev_interp = os.environ.get("PADDLE_TPU_KERNEL_INTERPRET")
    if not on_tpu:
        os.environ["PADDLE_TPU_KERNEL_INTERPRET"] = "1"
    try:
        if on_tpu:
            S, D, H, B, V, reps = 4096, 64, 12, 2, 50304, 5
        else:
            S, D, H, B, V, reps = 256, 32, 2, 1, 384, 2
        interp = not on_tpu
        sweep = kreg.autotune_flash(S, D, heads=H, batch=B,
                                    interpret=interp, persist=on_tpu)
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype("f4"))
                   for _ in range(3))
        g = jnp.asarray(rng.randn(B, S, H, D).astype("f4"))

        from paddle_tpu import observability as obs

        def sync(out):
            # honest-readback barrier (bench methodology contract): D2H
            # of a dependent scalar — never the device-side ready wait,
            # which is a no-op through the axon tunnel (commit 9ce47d5)
            leaf = jax.tree_util.tree_leaves(out)[0]
            _readback_sync(leaf.ravel()[0])

        def timed(surface, fn):
            sync(fn())                      # compile + warm
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sync(fn())
                times.append((time.perf_counter() - t0) * 1e3)
            med = statistics.median(times)
            obs.observe("pt_compile_dispatch_ms", med, surface=surface)
            return med

        measured = {}
        measured[kreg.FLASH_FWD_LSE_SURFACE] = timed(
            kreg.FLASH_FWD_LSE_SURFACE,
            lambda: fattn._flash_fwd_lse(q, k, v, None, causal=True,
                                         interpret=interp))
        o, lse = fattn._flash_fwd_lse(q, k, v, None, causal=True,
                                      interpret=interp)
        measured[kreg.FLASH_BWD_SURFACE] = timed(
            kreg.FLASH_BWD_SURFACE,
            lambda: fattn._flash_bwd(q, k, v, o, lse, g, None,
                                     causal=True, interpret=interp))
        T = B * S
        lg = jnp.asarray(rng.randn(T, V).astype("f4"))
        lb = jnp.asarray(rng.randint(0, V, (T,)).astype("i4"))
        force = fx._FORCE_INTERPRET
        fx._FORCE_INTERPRET = interp
        try:
            measured[kreg.XENT_FWD_SURFACE] = timed(
                kreg.XENT_FWD_SURFACE,
                lambda: fx.fused_softmax_xent(lg, lb))
            gfn = jax.grad(lambda x: jnp.sum(fx.fused_softmax_xent(x, lb)))
            measured[kreg.XENT_BWD_SURFACE] = timed(
                kreg.XENT_BWD_SURFACE, lambda: gfn(lg))
        finally:
            fx._FORCE_INTERPRET = force
        return {"autotune": sweep,
                "measured_ms": {s: round(m, 3)
                                for s, m in measured.items()},
                "shape": {"S": S, "D": D, "heads": H, "batch": B, "V": V},
                "interpret": interp, "measured": measured,
                "note": "kernel.xent_bwd times the grad dispatch "
                        "(fwd recompute + bwd kernel in one executable)"}
    finally:
        if prev_interp is None:
            os.environ.pop("PADDLE_TPU_KERNEL_INTERPRET", None)
        else:
            os.environ["PADDLE_TPU_KERNEL_INTERPRET"] = prev_interp


# ---------------------------------------------------------------------------
# ResNet-50: fwd+bwd+SGD-momentum, bf16 compute (BASELINE "ResNet-50 DP")
# ---------------------------------------------------------------------------

def bench_resnet50(B, iters):
    """r3 analysis vs BASELINE's 2.5-3.7k img/s/chip public anchor:
    measured v5e-1 ceiling here is ~2.4k at B=256 (2.1k in r2; the gain
    came from folding BN into one fused E[x]/E[x^2] pass + bf16 apply).
    r5 B-sweep re-check: 256 -> 2447, 320 -> 2174, 384 -> 2271,
    512 -> 2280 img/s — larger batches LOSE (activation HBM pressure),
    so B=256 stays the operating point.
    Why it tops out: ResNet-50's 1x1 bottleneck convs are HBM-bound
    (arith intensity ~Cout flops/byte -> roofline ~26% of bf16 peak),
    and the 3x3 convs reach only 16-25% of peak under the XLA conv
    emitter regardless of logical layout (NHWC == NCHW within noise).
    B=320/384/512 all measure lower than B=256.

    r4 closes the VERDICT #6 experiment with a measured three-way
    comparison at every bottleneck shape (B=256, latency-free 20-rep
    scan chains; ops/pallas/conv1x1.py is the fused kernel):
      - the Pallas fused conv+BN+ReLU kernel ties-or-beats BOTH XLA
        forms at 6/8 shapes (e.g. 5.46ms vs conv 8.55ms at 28x28
        128->512) and the plain dot form beats the conv emitter up to
        2.8x in isolation (3.26 vs 9.13ms at 56x56 64->256);
      - but wiring the dot form INTO the model measured 1858 img/s vs
        2344 with lax.conv (the NCHW transpose the isolated chain does
        not pay dominates), so the emitter stays;
      - all three forms sit far below even the HBM roofline in
        isolation (3-8% of peak) — the op is bandwidth/latency bound,
        and the remaining gap to the 2.5k+ anchors is the input-layout
        conversion economics of a single chip, not the lowering.
    The anchor numbers come from multi-chip runs whose per-chip batch
    and input pipeline differ; on this exact chip the bound is memory
    bandwidth, not our lowering."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    net = resnet50(num_classes=1000)
    net.train()
    params = [p for _, p in net.named_parameters()]
    buffers = [b for _, b in net.named_buffers()]
    pvals = [p._value for p in params]
    bvals = [b._value for b in buffers]

    def loss_fn(pv, bv, x, y):
        olds = [t._value for t in params + buffers]
        compute = [v.astype(jnp.bfloat16)
                   if jnp.issubdtype(v.dtype, jnp.floating) else v
                   for v in pv]
        for t, v in zip(params, compute):
            t._value = v
        for t, v in zip(buffers, bv):
            t._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                # input must match the bf16 params (lax.conv requires
                # uniform dtypes)
                logits = net(paddle.Tensor(x.astype(jnp.bfloat16))
                             )._value.astype(jnp.float32)
            new_bv = [t._value for t in buffers]
            logp = jax.nn.log_softmax(logits, -1)
            nll = -jnp.take_along_axis(logp, y[:, None], 1).mean()
            return nll, new_bv
        finally:
            for t, v in zip(params + buffers, olds):
                t._value = v

    lr, mom = 0.1, 0.9

    def step(pv, bv, vel, x, y):
        (loss, new_bv), g = jax.value_and_grad(loss_fn, has_aux=True)(
            pv, bv, x, y)
        new_p, new_vel = [], []
        for p, gi, vi in zip(pv, g, vel):
            nv = mom * vi + gi
            new_p.append(p - lr * nv)
            new_vel.append(nv)
        return loss, new_p, new_bv, new_vel

    step_jit = jax.jit(step, donate_argnums=(0, 1, 2))
    vel0 = [jnp.zeros_like(v) for v in pvals]
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, 3, 224, 224).astype("float32"))
    y = jnp.asarray(rng.randint(0, 1000, (B,)).astype("int32"))

    def run(pv, bv, vel):
        loss, pv, bv, vel = step_jit(pv, bv, vel, x, y)
        return loss, pv, bv, vel

    loss, pvals, bvals, vel0 = run(pvals, bvals, vel0)
    _readback_sync(loss)
    dt, final_loss, _ = _timeit(run, iters, pvals, bvals, vel0)
    return {"images_per_sec": round(iters * B / dt, 1),
            "loss": round(final_loss, 4), "batch": B}


# ---------------------------------------------------------------------------
# BERT-base: MLM-style train step with AMP O2 semantics (bf16 compute,
# fp32 master) — BASELINE "BERT-base DP+AMP"
# ---------------------------------------------------------------------------

def bench_bert(B, S, iters, peak):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.models import BertConfig, BertForPretraining

    paddle.seed(0)
    cfg = BertConfig()
    net = BertForPretraining(cfg)
    net.eval()  # p=0.0 dropout
    params = [p for _, p in net.named_parameters()]
    pvals = [p._value for p in params]

    def loss_fn(pv, ids, labels):
        olds = [p._value for p in params]
        compute = [v.astype(jnp.bfloat16)
                   if jnp.issubdtype(v.dtype, jnp.floating) else v
                   for v in pv]
        for p, v in zip(params, compute):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                out = net(paddle.Tensor(ids))
            logits = (out[0] if isinstance(out, (tuple, list))
                      else out)._value                    # bf16
            from paddle_tpu.ops.pallas.fused_xent import fused_softmax_xent
            V = logits.shape[-1]
            row = fused_softmax_xent(
                logits.reshape(-1, V),
                labels.reshape(-1).astype(jnp.int32))
            return row.mean()
        finally:
            for p, v in zip(params, olds):
                p._value = v

    lr = 1e-4
    K = int(os.environ.get("BENCH_STEPS_PER_CALL", "5"))

    def step(pv, ids, labels):
        loss, g = jax.value_and_grad(loss_fn)(pv, ids, labels)
        return loss, [p - lr * gi for p, gi in zip(pv, g)]

    def scan_steps(pv, ids, labels):
        def body(pv, _):
            loss, pv = step(pv, ids, labels)
            return pv, loss
        pv, losses = jax.lax.scan(body, pv, None, length=K)
        return losses[-1], pv

    step_jit = jax.jit(scan_steps, donate_argnums=(0,))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (B, S)).astype("int32"))

    def run(pv):
        loss, pv = step_jit(pv, ids, ids)
        return loss, pv

    loss, pvals = run(pvals)
    _readback_sync(loss)
    dt, final_loss, _ = _timeit(run, iters, pvals)
    tokens_per_sec = iters * K * B * S / dt
    n_params = sum(int(np.prod(p.shape)) for p in params)
    flops_per_tok = 6 * n_params \
        + 12 * cfg.num_hidden_layers * S * cfg.hidden_size  # bidirectional
    return {"tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(tokens_per_sec * flops_per_tok / peak, 4),
            "loss": round(final_loss, 4), "params": n_params,
            "batch": B, "seq": S}


# ---------------------------------------------------------------------------
# Eager-tape overhead: per-op vjp train step vs the jitted stepper on the
# same tiny model (VERDICT r1 weak #7 — make the eager path's cost known)
# ---------------------------------------------------------------------------

def bench_fp8_linear(M=32, K=4096, N=4096, layers=32, reps=1200):
    """Quantized-weight linear vs bf16 in the regime quantization
    targets: small-M (decode-style serving) where the matmul is
    WEIGHT-bandwidth-bound.

    r5 measurement fix (VERDICT r4 #1): every variant chains
    ``layers * reps`` linears inside ONE dispatch via nested lax.scan.
    r4 timed 20 *separate* async dispatches under the tunnel's ~95 ms
    dispatch latency, which is why the artifact said fp8_speedup 0.72
    at 85 GB/s while the README said 1.63x — both were latency noise.
    Scan-chained, latency-subtracted, repeat-stable truth (r5, v5e,
    this config at reps=1200): bf16 1.46 ms/pass (733 GB/s), weight-
    only fp8 0.88 ms (**1.66x**, 609 GB/s), int8-MXU Pallas 1.11 ms
    (1.32x).  v5e has no MXU fp8 arithmetic: the fp8 win is
    purely the 2x weight-HBM-traffic cut (XLA fuses the upconvert into
    its weight streaming); at large M (training) fp8 ~ties bf16 — that
    is why fp8_quantize targets deploy, not the train step.
    """
    import time
    import jax
    from jax import lax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.quant_matmul import (fp8_matmul,
                                                    fp8_quantize_weight,
                                                    int8_matmul)

    rng = np.random.RandomState(0)
    Wf = rng.randn(layers, K, N).astype("f4") * 0.02
    Wb = jnp.asarray(Wf, jnp.bfloat16)
    w8s = [fp8_quantize_weight(Wf[i]) for i in range(layers)]
    W8 = jnp.stack([w for w, _ in w8s])
    S8 = jnp.stack([s for _, s in w8s])
    sci = np.maximum(np.abs(Wf).max(axis=1) / 127.0, 1e-12)
    Wi = jnp.asarray(np.clip(np.round(Wf / sci[:, None, :]), -127, 127),
                     jnp.int8)
    Si = jnp.asarray(sci * 127.0, jnp.float32)  # int8_matmul scale convention
    x = jnp.asarray(rng.randn(M, K).astype("f4"), dtype=jnp.bfloat16)

    def chained(layer_fn):
        @jax.jit
        def run(x, *stacked):
            def rep(o, _):
                def one(o, ws):
                    return layer_fn(o, ws), None
                o, _ = lax.scan(one, o, stacked if len(stacked) > 1
                                else stacked[0])
                return o, None
            o, _ = lax.scan(rep, x, None, length=reps)
            return jnp.sum(o.astype(jnp.float32))
        return run

    run_bf16 = chained(lambda o, w: ((o @ w).astype(jnp.bfloat16) * 0.01))
    run_fp8 = chained(lambda o, ws: (fp8_matmul(
        o, ws[0], ws[1], out_dtype=jnp.bfloat16) * 0.01))
    run_i8 = chained(lambda o, ws: (int8_matmul(
        o, ws[0], ws[1], act_scale=8.0,
        out_dtype=jnp.bfloat16) * 0.01).astype(jnp.bfloat16))

    # dispatch-latency calibration for the validity flag
    dispatch_ms = _dispatch_latency_ms() or 0.0

    def timed(f, *stacked):
        _readback_sync(f(x, *stacked))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            _readback_sync(f(x, *stacked))
            ts.append((time.perf_counter() - t0) / reps)
        # subtract the (separately calibrated) per-dispatch latency share
        med = sorted(ts)[1] - dispatch_ms / 1e3 / reps
        return med, max(ts) / min(ts)

    t_bf16, j_bf16 = timed(run_bf16, Wb)
    t_fp8, j_fp8 = timed(run_fp8, W8, S8)
    t_i8, j_i8 = timed(run_i8, Wi, Si)
    latency_share = dispatch_ms / (reps * t_bf16 * 1e3 + dispatch_ms)
    return {"bf16_ms": round(t_bf16 * 1e3, 3),
            "fp8_ms": round(t_fp8 * 1e3, 3),
            "int8_ms": round(t_i8 * 1e3, 3),
            "fp8_speedup": round(t_bf16 / t_fp8, 3),
            "int8_speedup": round(t_bf16 / t_i8, 3),
            "fp8_weight_gbps": round(layers * K * N / t_fp8 / 1e9, 1),
            "bf16_weight_gbps": round(layers * K * N * 2 / t_bf16 / 1e9, 1),
            "repeat_jitter": {"bf16": round(j_bf16, 3),
                              "fp8": round(j_fp8, 3),
                              "int8": round(j_i8, 3)},
            "dispatch_latency_ms": round(dispatch_ms, 1),
            "latency_share_of_timing": round(latency_share, 4),
            # timings subtract the calibrated dispatch latency, so the
            # residual error is the latency JITTER (~2%) times the share;
            # <10% share keeps that under ~0.5% per-pass
            "valid": latency_share < 0.10,
            "shape": f"M{M} K{K} N{N} x{layers} reps{reps}"}


def bench_eager_overhead(iters=5):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = paddle.vision.models.LeNet()
    x = np.random.RandomState(0).rand(32, 1, 28, 28).astype("f4")
    y = np.random.RandomState(1).randint(0, 10, (32, 1)).astype("i8")
    loss_fn = nn.CrossEntropyLoss()

    def eager_step():
        opt = getattr(eager_step, "_opt", None)
        if opt is None:
            opt = paddle.optimizer.SGD(0.01, parameters=net.parameters())
            eager_step._opt = opt
        out = net(paddle.to_tensor(x))
        loss = loss_fn(out, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # warm + time eager (per-op tape, no jit)
    _readback_sync(eager_step()._value)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = eager_step()
    _readback_sync(loss._value)
    eager_dt = (time.perf_counter() - t0) / iters

    # jitted stepper via hapi Model on the same net/loss
    paddle.seed(0)
    net2 = paddle.vision.models.LeNet()
    model = paddle.Model(net2)
    model.prepare(paddle.optimizer.SGD(0.01,
                                       parameters=net2.parameters()),
                  nn.CrossEntropyLoss())
    model.train_batch([x], [y])  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        res = model.train_batch([x], [y])
    jit_dt = (time.perf_counter() - t0) / iters
    # through the axon tunnel EVERY op call pays dispatch latency, so
    # under congestion this ratio measures the tunnel, not the tape.
    # r5 (VERDICT r4 #9): the ratio is GATED on a healthy tunnel —
    # eager steps cannot be scan-chained (op-by-op dispatch is what
    # "eager" means), so when dispatch latency is high the only honest
    # output is the raw timings plus valid=False, never a ratio that
    # would be read as tape overhead (r4's latency-masked "1.1x").
    try:
        lat_ms = chip_calibration()["dispatch_latency_ms"]
    except Exception:
        lat_ms = None
    healthy = lat_ms is not None and lat_ms < 10.0 \
        and jit_dt * 1e3 >= 3 * lat_ms
    out = {"eager_ms": round(eager_dt * 1e3, 2),
           "jit_ms": round(jit_dt * 1e3, 2),
           "eager_over_jit": (round(eager_dt / max(jit_dt, 1e-9), 1)
                              if healthy else None),
           "dispatch_latency_ms": lat_ms,
           "valid": healthy}
    if not healthy:
        out["invalid_reason"] = (
            "latency-bound: dispatch latency too high to attribute the "
            "eager/jit delta to the tape (need <10ms and jit step >= 3x "
            "latency); last trustworthy reading: 1.7x (r3)")
    return out


# ---------------------------------------------------------------------------
# GPT-3 1.3B hybrid (the BASELINE north-star config): dp x mp sharded via
# GSPMD.  Runs whenever >1 chip is visible; on 1 chip the same config is
# re-exec'd as a subprocess onto an 8-virtual-device CPU mesh
# (--xla_force_host_platform_device_count, the conftest trick) at proxy
# scale — explicitly labeled cpu_proxy — instead of returning skipped.
# ---------------------------------------------------------------------------

def bench_gpt1p3b_hybrid(iters=5, peak=197e12, hidden=2048, layers=24,
                         heads=16, seq=1024, vocab=50304, per_dp_batch=4):
    import jax

    from paddle_tpu.models import GPTConfig

    n = jax.device_count()
    if n < 2:
        return _hybrid_cpu_proxy()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.models import GPTForPretraining

    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_hidden_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=seq)
    mp = 2 if n % 2 == 0 else 1
    dp = n // mp
    B, S = dp * per_dp_batch, seq
    mesh = Mesh(np.asarray(jax.devices()[:dp * mp]).reshape(dp, mp),
                ("data", "model"))
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()
    params = [p for _, p in net.named_parameters()]

    def shard(p):
        spec = [None] * len(p.shape)
        if len(p.shape) == 2 and int(np.prod(p.shape)) >= hidden * hidden:
            spec[-1] = "model"  # column-shard the big matmuls
        return NamedSharding(mesh, P(*spec))
    pvals = [jax.device_put(p._value, shard(p)) for p in params]

    def forward_pure(pv, ids):
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                return net(paddle.Tensor(ids))._value
        finally:
            for p, v in zip(params, olds):
                p._value = v

    def loss_fn(pv, ids):
        compute = [v.astype(jnp.bfloat16)
                   if jnp.issubdtype(v.dtype, jnp.floating) else v
                   for v in pv]
        logits = forward_pure(compute, ids)
        V = logits.shape[-1]
        lg = logits[:, :-1, :].reshape(-1, V)
        lb = ids[:, 1:].reshape(-1)
        m = jnp.max(lg, axis=-1)
        ex = jnp.exp((lg - m[:, None]).astype(jnp.float32))
        lse = m.astype(jnp.float32) + jnp.log(jnp.sum(ex, axis=-1))
        picked = jnp.take_along_axis(lg, lb[:, None], 1)[:, 0]
        return (lse - picked.astype(jnp.float32)).mean()

    lr = 1e-4

    def step(pv, ids):
        loss, g = jax.value_and_grad(loss_fn)(pv, ids)
        return loss, [p - lr * gi for p, gi in zip(pv, g)]

    step_jit = jax.jit(step, donate_argnums=(0,))
    rng = np.random.RandomState(0)
    ids = jax.device_put(
        jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S), dtype=np.int32)),
        NamedSharding(mesh, P("data", None)))
    loss, pvals = step_jit(pvals, ids)
    _readback_sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, pvals = step_jit(pvals, ids)
    final = _readback_sync(loss)
    dt = time.perf_counter() - t0
    tps = iters * B * S / dt
    n_params = sum(int(np.prod(p.shape)) for p in params)
    fpt = 6 * n_params + 6 * cfg.num_hidden_layers * S * cfg.hidden_size
    return {"tokens_per_sec": round(tps, 1),
            "tokens_per_sec_per_chip": round(tps / (dp * mp), 1),
            "mfu": round(tps * fpt / (peak * dp * mp), 4),
            "loss": round(final, 4), "params": n_params,
            "dp": dp, "mp": mp, "batch": B, "seq": S}


def _hybrid_cpu_proxy(timeout_s=900):
    """One visible chip: re-exec this file onto a simulated 8-device CPU
    mesh (``--xla_force_host_platform_device_count=8``) and measure the
    hybrid config at proxy scale there.  The result is explicitly
    labeled ``cpu_proxy`` — it proves the dp x mp wire pattern and the
    grad_comm bucketed/quantized reducer end to end and gives honest
    *relative* numbers (per-collective bytes, wire-format ratios), not
    TPU throughput."""
    import subprocess
    import sys

    if os.environ.get("BENCH_HYBRID_CHILD"):
        # recursion guard: we ARE the re-exec'd child yet still see <2
        # devices (e.g. the caller's XLA_FLAGS pins its own
        # host_platform_device_count) — report, never fork again
        return {"error": "cpu-proxy child still sees <2 devices; check "
                         "XLA_FLAGS for a conflicting "
                         "host_platform_device_count"}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_HYBRID_CHILD"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the TPU tunnel
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    here = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--hybrid-cpu-proxy"],
            capture_output=True, text=True, env=env, cwd=here,
            timeout=timeout_s)
        if proc.returncode != 0:
            return {"error": "cpu-proxy subprocess failed: "
                             + (proc.stderr or "")[-300:]}
        child = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": f"cpu-proxy subprocess: {repr(e)[:200]}"}
    return {"cpu_proxy": True,
            "note": "1 chip visible: measured on a simulated 8-device "
                    "CPU mesh at proxy model scale — wire pattern and "
                    "byte ratios are real, absolute tokens/sec is CPU",
            **child}


def _bench_grad_comm_wire_modes(iters=3, B=8, S=128):
    """Pure-DP proxy GPT through the hapi grad_comm stepper, once per
    wire format (fp32 psum / bf16 / int8 quantized), on the current
    (8-virtual-device) mesh.  Per-collective bytes come from the
    ``pt_collective_bytes_total`` counters — ticked per *tracing*, so
    each mode's number is its per-replica wire bytes for one step.  The
    registry is NOT reset between modes: each mode's ops have distinct
    names, so one final telemetry snapshot carries the whole fp32-vs-
    quantized comparison."""
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.fleet.base.distributed_strategy import \
        DistributedStrategy
    from paddle_tpu.models import (GPTConfig, GPTForPretraining,
                                   GPTPretrainingCriterion)

    cfg = GPTConfig(vocab_size=4096, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=S)
    obs.get_registry().reset()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype("i4")
    out = {}
    for mode in (None, "bf16", "int8"):
        st = DistributedStrategy()
        st.grad_comm = True
        st.grad_comm_configs = {"bucket_mb": 0.25, "overlap": True,
                                "quantize": mode}
        paddle.seed(0)
        net = GPTForPretraining(cfg)
        net.eval()  # p=0 dropout: mask-free graph, math == train()
        dp = paddle.DataParallel(net, strategy=st)
        model = paddle.Model(dp)
        model.prepare(paddle.optimizer.AdamW(
            1e-4, parameters=net.parameters()), GPTPretrainingCriterion())
        model.train_batch([ids], [ids])  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            res = model.train_batch([ids], [ids])
        _readback_sync(res[0] if isinstance(res, (list, tuple)) else res)
        dt = time.perf_counter() - t0
        bytes_m = obs.get_registry().get("pt_collective_bytes_total")
        per_op = {lbl["op"]: int(v) for lbl, v in bytes_m.series()
                  if lbl["op"].startswith("grad_")} if bytes_m else {}
        ops = {"bf16": ("grad_bucket_psum_bf16",),
               "int8": ("grad_quant_all_to_all", "grad_quant_all_gather"),
               }.get(mode, ("grad_bucket_psum",))
        out[mode or "fp32"] = {
            "tokens_per_sec": round(iters * B * S / dt, 1),
            "wire_bytes_per_step": sum(per_op.get(o, 0) for o in ops),
            "ops": {o: per_op.get(o, 0) for o in ops},
        }
    fp32_b = out["fp32"]["wire_bytes_per_step"]
    for mode in ("bf16", "int8"):
        if fp32_b:
            out[mode]["wire_bytes_vs_fp32"] = round(
                out[mode]["wire_bytes_per_step"] / fp32_b, 4)
    return out


def _hybrid_cpu_proxy_child():
    """Child entry (``bench.py --hybrid-cpu-proxy``): runs on the forced
    8-device CPU mesh, prints ONE JSON line for the parent."""
    import jax

    # the axon sitecustomize re-registers the TPU tunnel at interpreter
    # start (clobbering JAX_PLATFORMS) — pin CPU again before backends
    # initialize, exactly as tests/conftest.py does
    jax.config.update("jax_platforms", "cpu")
    out = {"devices": jax.device_count(),
           "mesh": "xla_force_host_platform_device_count=8"}
    hybrid = bench_gpt1p3b_hybrid(iters=3, peak=1e12, hidden=256,
                                  layers=4, heads=8, seq=256, vocab=8192,
                                  per_dp_batch=2)
    hybrid["proxy_model"] = "hidden=256 L=4 heads=8 S=256 V=8192"
    out["hybrid_gspmd"] = hybrid
    try:
        out["grad_comm"] = _bench_grad_comm_wire_modes()
    except Exception as e:
        out["grad_comm"] = {"error": repr(e)[:200]}
    else:
        # _telemetry_snapshot reports its own failure inline; never let
        # a sink problem overwrite the computed wire-mode comparison
        out["telemetry"] = _telemetry_snapshot("hybrid_proxy")
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# Autoregressive decode (serving): GPT-125M bf16 greedy generation with the
# static KV cache — prefill + the whole token-by-token scan is ONE compiled
# dispatch, so the number is latency-robust by construction.
# ---------------------------------------------------------------------------

def bench_decode(B=8, P=128, N=128, iters=3):
    """Measured r5: bf16 1.22-1.44 ms/step.  fp8-quantizing the model
    (quantization.fp8_quantize + generate, measured directly) TIES bf16
    here (1.25 vs 1.22 ms/step): at 768-wide layers the decode step is
    not weight-bandwidth-dominated, so halving matmul weight bytes
    doesn't move it — the fp8 serving win needs the K=N=4096-class
    layers the fp8_linear config measures (1.66x there).  A 1.3B-scale
    decode (where the weight stream WOULD dominate) could not be
    measured: the 24-layer x 128-step scan program exceeds what the
    axon remote-compile tunnel will take (broken pipe both attempts);
    single-op compiles still work after, so it is program size, not
    chip state."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    max_position_embeddings=P + N)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (B, P)).astype("int32"))
    out, _ = net.generate(ids, max_new_tokens=N, dtype="bfloat16")
    _readback_sync(out._value[:, -1].astype("float32").sum())  # warmup
    t0 = time.perf_counter()
    for i in range(iters):
        out, _ = net.generate(ids, max_new_tokens=N, dtype="bfloat16",
                              seed=i)
        _readback_sync(out._value[:, -1].astype("float32").sum())
    dt = time.perf_counter() - t0
    decode_tps = iters * B * N / dt
    return {"decode_tokens_per_sec": round(decode_tps, 1),
            "ms_per_step": round(dt / (iters * N) * 1e3, 3),
            "batch": B, "prompt": P, "new_tokens": N,
            "model": "gpt125m", "dtype": "bfloat16"}


# ---------------------------------------------------------------------------
# Serving: continuous-batching engine vs static-batch generate() on a
# mixed-length request trace — the workload where static batching burns
# slots on drained rows (ISSUE 4 tentpole).
# ---------------------------------------------------------------------------

def bench_serving(n_requests=64, seed=0, hidden=768, layers=12, heads=12,
                  p_range=(32, 512), n_range=(16, 256), slots=8, chunk=32,
                  p_lams=(48, 96, 192, 384), n_lams=(24, 64, 160)):
    """Mixed-length trace (prompts 32-512, new-tokens 16-256, both
    log-uniform-ish via Poisson-mixed geometric draws) through:

      1. the static-batch baseline: FCFS groups of 8 through
         ``generate()``, prompts left-padded (attention_mask) to the
         group's power-of-two bucket and every row decoding the group's
         max budget rounded up to a bucket — the padding/drain waste is
         the point, but bucketing keeps the compile count bounded;
      2. the continuous-batching ``ServingEngine`` (8 slots, chunk=32)
         over the identical requests.

    Both run the full trace once to compile (programs cache), then the
    timed pass.  tokens/sec counts USEFUL tokens only (each request's
    own budget).  Validity mirrors eager_overhead: the engine pays one
    dispatch per chunk + one per prefill, so when the calibrated
    dispatch latency accounts for >30% of the engine's wall the ratio
    measures the tunnel, not the scheduler — reported with
    ``valid=False`` + ``invalid_reason`` instead of a hollow speedup.
    """
    import jax  # noqa: F401  (device selection side effects)

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def bucket(n, lo):
        b = lo
        while b < n:
            b *= 2
        return b

    GROUP = slots
    p_lo, p_hi = p_range
    n_lo, n_hi = n_range
    max_seq = bucket(p_hi, p_lo) + bucket(n_hi, n_lo)
    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden,
                    num_hidden_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_seq)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()

    rng = np.random.RandomState(seed)
    # Poisson-mixed lengths, clipped into the brief's ranges
    plens = np.clip(rng.poisson(lam=rng.choice(p_lams, size=n_requests)),
                    p_lo, p_hi).astype(int)
    budgets = np.clip(rng.poisson(lam=rng.choice(n_lams, size=n_requests)),
                      n_lo, n_hi).astype(int)
    prompts = [rng.randint(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in plens]
    useful = int(budgets.sum())

    def run_static():
        done_tokens = 0
        ttfts = []
        t_start = time.perf_counter()
        for g in range(0, n_requests, GROUP):
            gp = prompts[g:g + GROUP]
            gb = budgets[g:g + GROUP]
            P = bucket(max(p.size for p in gp), p_lo)
            N = bucket(int(gb.max()), n_lo)
            ids = np.zeros((len(gp), P), np.int32)
            mask = np.zeros((len(gp), P), np.int32)
            for i, p in enumerate(gp):          # left-pad to the bucket
                ids[i, P - p.size:] = p
                mask[i, P - p.size:] = 1
            out, _ = net.generate(paddle.to_tensor(ids),
                                  max_new_tokens=N, dtype="bfloat16",
                                  attention_mask=mask)
            # completion barrier: data-dependent readback (never
            # a tunnel-noop wait primitive)
            _readback_sync(out._value[:, -1].astype("float32").sum())
            now = time.perf_counter()
            # a static group's tokens all materialize when the group
            # returns; only each row's own budget counts as useful
            done_tokens += int(gb.sum())
            ttfts.extend([(now - t_start) * 1e3] * len(gp))
        wall = time.perf_counter() - t_start
        return done_tokens / wall, sum(ttfts) / len(ttfts), wall

    def run_engine(eng):
        eng.reset()
        t_start = time.perf_counter()
        for p, b in zip(prompts, budgets):
            eng.submit(p, int(b))
        eng.run()
        wall = time.perf_counter() - t_start
        tt = eng.stats["ttft_ms"]
        return (eng.stats["decoded_tokens"] / wall,
                sum(tt) / len(tt), wall)

    # the engine's default power-of-two buckets (16..<max_seq) cover the
    # trace; buckets no prompt lands in never trace (jax.jit is lazy)
    eng = ServingEngine(net, num_slots=GROUP, chunk=chunk,
                        max_seq_len=max_seq, dtype="bfloat16")
    # compile passes (programs cache on the model / in the engine)
    run_engine(eng)
    run_static()
    static_tps, static_ttft, _ = run_static()
    # timed pass runs with the flight recorder watching (ISSUE 13):
    # sampling is host-only at the existing chunk sync, so it is free
    # at bench fidelity — and a healthy bench run must raise ZERO watch
    # alerts, which the committed bench line records
    from paddle_tpu.framework import guardian as _guardian
    from paddle_tpu.observability import flight as _flight
    _alerts0 = len(_guardian.events("watch_alert"))
    # dump_dir=False: alerts-only, so a rule trip can never start disk
    # I/O inside the timed region even when PADDLE_FLIGHT_DIR is set;
    # and never stomp a recorder the user installed via PADDLE_FLIGHT=1
    _owned = not _flight.active()
    _rec = _flight.enable(dump_dir=False) if _owned \
        else _flight.recorder()
    try:
        engine_tps, engine_ttft, engine_wall = run_engine(eng)
        watch_alerts = len(_guardian.events("watch_alert")) - _alerts0
        flight_samples = len(_rec.samples())
    finally:
        if _owned:
            _flight.disable()

    lat_ms = _dispatch_latency_ms()
    n_dispatch = eng.stats["chunks"] + eng.stats["prefills"]
    lat_share = None if lat_ms is None else \
        min(n_dispatch * lat_ms / 1e3 / max(engine_wall, 1e-9), 1.0)
    healthy = lat_share is not None and lat_share < 0.30
    out = {"engine_tokens_per_sec": round(engine_tps, 1),
           "static_tokens_per_sec": round(static_tps, 1),
           "speedup": round(engine_tps / max(static_tps, 1e-9), 3),
           "engine_mean_ttft_ms": round(engine_ttft, 1),
           "static_mean_ttft_ms": round(static_ttft, 1),
           "useful_tokens": useful,
           "requests": n_requests, "slots": GROUP, "chunk": chunk,
           "chunks": eng.stats["chunks"],
           "prefills": eng.stats["prefills"],
           "flight_samples": flight_samples,
           "watch_alerts": watch_alerts,
           "dispatch_latency_ms": lat_ms,
           "latency_share_of_engine_wall": (round(lat_share, 4)
                                            if lat_share is not None
                                            else None),
           "valid": healthy,
           "model": f"gpt_h{hidden}_l{layers}", "dtype": "bfloat16"}
    if not healthy:
        out["invalid_reason"] = (
            "latency-bound: per-chunk/prefill dispatch latency accounts "
            "for >=30% of the engine's wall clock, so the ratio measures "
            "the axon tunnel, not continuous batching")
    return out


# ---------------------------------------------------------------------------
# Serving, prefix-heavy: 64 requests sharing one system prompt — the
# workload the paged KV subsystem (ISSUE 7) exists for.  Dense re-prefills
# the shared prompt per request and holds S x MAX KV regardless of
# occupancy; paged prefills it once (prefix cache) and keeps only live
# pages resident.
# ---------------------------------------------------------------------------

def bench_serving_prefix(n_requests=64, seed=0, hidden=768, layers=12,
                         heads=12, sys_len=256, sfx_range=(8, 48),
                         n_range=(16, 64), slots=8, chunk=32,
                         page_size=16):
    """The same engine/trace/validity discipline as ``bench_serving``,
    but every request is ``system_prompt + unique_suffix`` and the trace
    runs through three engines — dense, paged, paged+int8 — reporting:

    - prefix hit-rate and prefill tokens actually computed (the FLOPs
      saved is proportional: prefill FLOPs ~ 2 * params * tokens);
    - KV HBM high-water: dense's static ``S x MAX`` allocation vs the
      paged pool's resident high-water (``pt_kvcache_*`` gauges);
    - useful tokens/sec per mode (same dispatch-latency validity gate).

    Token parity between dense and paged is asserted, not reported —
    a perf number for a wrong answer is worthless.
    """
    import jax  # noqa: F401

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def bucket(n, lo=16):
        b = lo
        while b < n:
            b *= 2
        return b

    max_seq = bucket(sys_len + sfx_range[1]) + bucket(n_range[1])
    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden,
                    num_hidden_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_seq)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()

    rng = np.random.RandomState(seed)
    sysp = rng.randint(0, cfg.vocab_size, (sys_len,)).astype("int32")
    prompts = [np.concatenate([sysp, rng.randint(
        0, cfg.vocab_size,
        (int(rng.randint(*sfx_range)),)).astype("int32")])
        for _ in range(n_requests)]
    budgets = rng.randint(*n_range, size=n_requests)
    useful = int(budgets.sum())
    prompt_tokens = int(sum(p.size for p in prompts))

    def run(eng):
        eng.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, int(b)) for p, b in zip(prompts, budgets)]
        eng.run()
        wall = time.perf_counter() - t0
        return reqs, eng.stats["decoded_tokens"] / wall, wall

    def dense_kv_bytes(eng):
        # the dense engine's static per-layer (S, MAX, nH, D) K+V rows
        return sum(2 * k.nbytes for k, _ in eng._caches)

    results, walls, dispatches, baseline = {}, [], [], None
    modes = (("dense", {}),
             ("paged", {"kv_mode": "paged", "page_size": page_size}),
             ("paged_int8", {"kv_mode": "paged", "page_size": page_size,
                             "kv_dtype": "int8"}))
    for name, kw in modes:
        eng = ServingEngine(net, num_slots=slots, chunk=chunk,
                            max_seq_len=max_seq, dtype="bfloat16", **kw)
        run(eng)                                    # compile pass
        reqs, tps, wall = run(eng)
        walls.append(wall)
        dispatches.append(eng.stats["chunks"] + eng.stats["prefills"])
        toks = [list(r.tokens) for r in sorted(reqs,
                                               key=lambda r: r.req_id)]
        if name == "dense":
            baseline = toks
            results[name] = {
                "tokens_per_sec": round(tps, 1),
                "kv_hbm_high_water_bytes": dense_kv_bytes(eng),
                "prefill_tokens_computed": prompt_tokens}
        else:
            if name == "paged":
                # full precision must be BITWISE; int8 is tolerance-
                # bounded (docs/serving.md) and reported, not asserted
                assert toks == baseline, \
                    "paged engine output diverged from dense"
            kv = eng._kv
            hits = kv.stats["prefix_hits"]
            saved = kv.stats["prefix_saved_tokens"]
            results[name] = {
                "tokens_per_sec": round(tps, 1),
                "kv_hbm_high_water_bytes":
                    kv.stats["resident_high_water_bytes"],
                "prefix_hit_rate": round(hits / n_requests, 3),
                "prefill_tokens_computed": prompt_tokens - saved,
                "prefill_tokens_saved": saved,
                "prefill_flops_saved_frac":
                    round(saved / prompt_tokens, 3),
                "page_evictions": eng.stats["page_evictions"]}
            if name == "paged_int8":
                agree = [int(a == b) for ta, tb in zip(toks, baseline)
                         for a, b in zip(ta, tb)]
                results[name]["token_agreement_vs_dense"] = round(
                    sum(agree) / max(len(agree), 1), 4)
        del eng

    # dispatch-latency validity gate (same probe as bench_serving)
    lat_ms = _dispatch_latency_ms()
    lat_share = None if lat_ms is None else \
        min(max(d * lat_ms / 1e3 / max(w, 1e-9)
                for d, w in zip(dispatches, walls)), 1.0)
    healthy = lat_share is not None and lat_share < 0.30
    dense_hw = results["dense"]["kv_hbm_high_water_bytes"]
    out = {"modes": results,
           "kv_hbm_paged_over_dense": round(
               results["paged"]["kv_hbm_high_water_bytes"] / dense_hw, 4),
           "kv_hbm_paged_int8_over_dense": round(
               results["paged_int8"]["kv_hbm_high_water_bytes"]
               / dense_hw, 4),
           "requests": n_requests, "shared_prefix_len": sys_len,
           "useful_tokens": useful, "slots": slots, "chunk": chunk,
           "page_size": page_size,
           "dispatch_latency_ms": lat_ms,
           "latency_share_of_engine_wall": (round(lat_share, 4)
                                            if lat_share is not None
                                            else None),
           "valid": healthy,
           "model": f"gpt_h{hidden}_l{layers}", "dtype": "bfloat16"}
    if not healthy:
        out["invalid_reason"] = (
            "latency-bound: per-chunk/prefill dispatch latency accounts "
            "for >=30% of an engine's wall clock, so mode ratios "
            "measure the axon tunnel, not the KV subsystem")
    return out


# ---------------------------------------------------------------------------
# Serving, speculative: the SAME Poisson trace as `serving`, replayed with
# and without draft-verify speculation on both KV modes (ISSUE 8).  Decode
# is dispatch-bound here (~95-105ms per axon call); speculation multiplies
# tokens-per-dispatch by the accepted draft length, so the win shows up as
# useful tokens/sec on an identical-output run.
# ---------------------------------------------------------------------------

def bench_serving_spec(n_requests=64, seed=0, hidden=768, layers=12,
                       heads=12, p_range=(32, 512), n_range=(16, 256),
                       slots=8, chunk=32, gamma=4, ngram=3, page_size=16,
                       p_lams=(48, 96, 192, 384), n_lams=(24, 64, 160)):
    """Four engines over one trace — dense, dense+spec, paged,
    paged+spec — using the model-free n-gram prompt-lookup drafter (no
    second network to keep honest; the draft-model path is covered by
    tests).  Greedy speculative output is asserted BITWISE equal to the
    non-speculative engine per KV mode (a speedup for a different
    answer is worthless), acceptance telemetry is reported from
    ``engine.stats``, and the same dispatch-latency validity gate as
    ``serving`` guards the ratios."""
    import jax  # noqa: F401

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.speculative import SpecConfig
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def bucket(n, lo):
        b = lo
        while b < n:
            b *= 2
        return b

    p_lo, p_hi = p_range
    n_lo, n_hi = n_range
    max_seq = bucket(p_hi, p_lo) + bucket(n_hi, n_lo)
    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden,
                    num_hidden_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_seq)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()
    rng = np.random.RandomState(seed)
    plens = np.clip(rng.poisson(lam=rng.choice(p_lams, size=n_requests)),
                    p_lo, p_hi).astype(int)
    budgets = np.clip(rng.poisson(lam=rng.choice(n_lams, size=n_requests)),
                      n_lo, n_hi).astype(int)
    prompts = [rng.randint(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in plens]
    useful = int(budgets.sum())

    def run(eng):
        eng.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, int(b)) for p, b in zip(prompts, budgets)]
        eng.run()
        wall = time.perf_counter() - t0
        toks = [list(r.tokens) for r in sorted(reqs,
                                               key=lambda r: r.req_id)]
        return toks, eng.stats["decoded_tokens"] / wall, wall

    spec = SpecConfig(gamma=gamma, ngram=ngram)
    modes = (("dense", {}),
             ("dense_spec", {"spec_decode": spec}),
             ("paged", {"kv_mode": "paged", "page_size": page_size}),
             ("paged_spec", {"kv_mode": "paged", "page_size": page_size,
                             "spec_decode": spec}))
    results, walls, dispatches, baseline = {}, {}, {}, {}
    for name, kw in modes:
        eng = ServingEngine(net, num_slots=slots, chunk=chunk,
                            max_seq_len=max_seq, dtype="bfloat16", **kw)
        run(eng)                                    # compile pass
        toks, tps, wall = run(eng)
        walls[name] = wall
        dispatches[name] = eng.stats["chunks"] + eng.stats["prefills"]
        res = {"tokens_per_sec": round(tps, 1),
               "chunks": eng.stats["chunks"],
               "prefills": eng.stats["prefills"]}
        if kw.get("spec_decode") is not None:
            base = name.split("_")[0]
            # the parity contract IS the product: bitwise or bust
            assert toks == baseline[base], \
                f"speculative {base} output diverged from {base}"
            prop = eng.stats["spec_proposed"]
            acc = eng.stats["spec_accepted"]
            part = prop // gamma                # slot-steps, not steps
            res.update({
                "speedup_vs_base": round(
                    tps / max(results[base]["tokens_per_sec"], 1e-9), 3),
                "gamma": gamma, "ngram": ngram,
                "proposed": prop, "accepted": acc,
                "accept_rate": round(acc / prop, 4) if prop else None,
                "mean_accept_len": round(acc / part, 3) if part
                else None,
                "tokens_per_dispatch": round(
                    useful / max(dispatches[name], 1), 2)})
        else:
            baseline[name] = toks
            res["tokens_per_dispatch"] = round(
                useful / max(dispatches[name], 1), 2)
        results[name] = res
        del eng

    lat_ms = _dispatch_latency_ms()
    lat_share = None if lat_ms is None else \
        min(max(d * lat_ms / 1e3 / max(walls[n], 1e-9)
                for n, d in dispatches.items()), 1.0)
    healthy = lat_share is not None and lat_share < 0.30
    out = {"modes": results,
           "speedup_dense": results["dense_spec"]["speedup_vs_base"],
           "speedup_paged": results["paged_spec"]["speedup_vs_base"],
           "requests": n_requests, "useful_tokens": useful,
           "slots": slots, "chunk": chunk, "gamma": gamma,
           "dispatch_latency_ms": lat_ms,
           "latency_share_of_engine_wall": (round(lat_share, 4)
                                            if lat_share is not None
                                            else None),
           "valid": healthy,
           "model": f"gpt_h{hidden}_l{layers}", "dtype": "bfloat16"}
    if not healthy:
        out["invalid_reason"] = (
            "latency-bound: per-chunk/prefill dispatch latency accounts "
            "for >=30% of an engine's wall clock, so spec ratios measure "
            "the axon tunnel, not draft-verify speculation")
    return out


# ---------------------------------------------------------------------------
# Serving, quantized weights: the SAME Poisson trace through base,
# int8-weight and fp8-weight engines (ISSUE 19).  Decode is weight-
# stream-bound, so shrinking resident weight bytes is the lever; the
# measured token-agreement rate vs the base stream is reported next to
# every ratio (docs/serving.md "Quantized decode": floor >= 99%).
# ---------------------------------------------------------------------------

def bench_serving_quant(n_requests=64, seed=0, hidden=768, layers=12,
                        heads=12, p_range=(32, 512), n_range=(16, 256),
                        slots=8, chunk=32, dtype="bfloat16",
                        p_lams=(48, 96, 192, 384), n_lams=(24, 64, 160)):
    """Three engines over ONE trace — base (``dtype``), int8 weights,
    fp8 weights — same trace/validity discipline as ``bench_serving``.
    Reports useful tokens/sec per mode, speedup vs base, the MEASURED
    token-agreement rate against the base greedy stream (quantization
    changes the model, so agreement is a reported number, not an
    assert), and the ``pt_serving_quant_bytes_saved`` gauge per mode.
    The dispatch-latency validity gate guards the ratios exactly as in
    ``serving``."""
    import jax  # noqa: F401

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def bucket(n, lo):
        b = lo
        while b < n:
            b *= 2
        return b

    p_lo, p_hi = p_range
    n_lo, n_hi = n_range
    max_seq = bucket(p_hi, p_lo) + bucket(n_hi, n_lo)
    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden,
                    num_hidden_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_seq)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()
    rng = np.random.RandomState(seed)
    plens = np.clip(rng.poisson(lam=rng.choice(p_lams, size=n_requests)),
                    p_lo, p_hi).astype(int)
    budgets = np.clip(rng.poisson(lam=rng.choice(n_lams, size=n_requests)),
                      n_lo, n_hi).astype(int)
    prompts = [rng.randint(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in plens]
    useful = int(budgets.sum())

    def run(eng):
        eng.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, int(b)) for p, b in zip(prompts, budgets)]
        eng.run()
        wall = time.perf_counter() - t0
        toks = [list(r.tokens) for r in sorted(reqs,
                                               key=lambda r: r.req_id)]
        return toks, eng.stats["decoded_tokens"] / wall, wall

    def agreement(a, b):
        """(free-running agreement, mean prefix-agreement).  Greedy
        decode on a random-init model is chaotic — near-flat logit
        margins mean ONE quant-flipped argmax diverges the whole tail,
        so the free-running rate is a lower bound that collapses with
        sequence length; the prefix rate (tokens before the first
        divergence) is the per-decision number.  Per-step decision
        fidelity at trained-margin scales is machine-checked at >=99%
        in tests/test_quant_paths.py."""
        n = d = 0
        prefixes = []
        for x, y in zip(a, b):
            first = None
            for i, (u, v) in enumerate(zip(x, y)):
                d += 1
                if u == v:
                    n += 1
                elif first is None:
                    first = i
            prefixes.append((len(x) if first is None else first)
                            / max(len(x), 1))
        return n / max(d, 1), sum(prefixes) / max(len(prefixes), 1)

    from paddle_tpu.observability import get_registry
    modes = (("base", None), ("int8", "int8"), ("fp8", "fp8"))
    results, walls, dispatches, base_toks = {}, {}, {}, None
    for name, qmode in modes:
        eng = ServingEngine(net, num_slots=slots, chunk=chunk,
                            max_seq_len=max_seq, dtype=dtype,
                            quant_mode=qmode)
        saved = None
        if qmode is not None:
            g = get_registry().get("pt_serving_quant_bytes_saved")
            saved = int(g.value()) if g is not None else None
        run(eng)                                    # compile pass
        toks, tps, wall = run(eng)
        walls[name] = wall
        dispatches[name] = eng.stats["chunks"] + eng.stats["prefills"]
        res = {"useful_tokens_per_sec": round(tps, 1),
               "chunks": eng.stats["chunks"],
               "prefills": eng.stats["prefills"]}
        if qmode is None:
            base_toks = toks
        else:
            agree, prefix = agreement(base_toks, toks)
            res.update({
                "speedup_vs_base": round(
                    tps / max(results["base"]["useful_tokens_per_sec"],
                              1e-9), 3),
                "token_agreement_vs_base": round(agree, 4),
                "prefix_agreement_vs_base": round(prefix, 4),
                "quant_bytes_saved": saved})
        results[name] = res
        del eng

    # One eager dispatch per mode at the decode-head shape (M=slots,
    # K=hidden, N=vocab): engine-traced quant_matmul calls inline into
    # the serving.decode_chunk surface, so the roofline's standalone
    # `kernel.quant_matmul` row comes from this measured dispatch.
    import jax.numpy as jnp
    from paddle_tpu.ops import quant_dispatch as _qd
    table = jnp.asarray(net.tied_lm_head._value).T      # (H, V)
    x_dec = jnp.asarray(rng.randn(slots, hidden).astype("float32"))
    for m in ("int8", "fp8"):
        np.asarray(_qd.quant_matmul(x_dec, _qd.quantize_weight(table, m)))

    lat_ms = _dispatch_latency_ms()
    lat_share = None if lat_ms is None else \
        min(max(d * lat_ms / 1e3 / max(walls[n], 1e-9)
                for n, d in dispatches.items()), 1.0)
    healthy = lat_share is not None and lat_share < 0.30
    out = {"modes": results,
           "speedup_int8": results["int8"]["speedup_vs_base"],
           "speedup_fp8": results["fp8"]["speedup_vs_base"],
           "agreement_int8": results["int8"]["token_agreement_vs_base"],
           "agreement_fp8": results["fp8"]["token_agreement_vs_base"],
           # the kernel-level uplift on real accelerator silicon, from
           # the scan-chained latency-subtracted fp8_linear row (r5,
           # v5e, M=32 K=N=4096): the CPU proxy reproduces the int8
           # weight-stream win via the tiled off-TPU lowering, but the
           # fp8 upconvert is software-emulated there, so the fp8
           # column's deploy-path truth lives in these numbers
           "kernel_uplift_v5e": {"fp8": 1.66, "int8": 1.32,
                                 "source": "fp8_linear r5"},
           "requests": n_requests, "useful_tokens": useful,
           "slots": slots, "chunk": chunk,
           "dispatch_latency_ms": lat_ms,
           "latency_share_of_engine_wall": (round(lat_share, 4)
                                            if lat_share is not None
                                            else None),
           "valid": healthy,
           "model": f"gpt_h{hidden}_l{layers}", "dtype": dtype}
    if not healthy:
        out["invalid_reason"] = (
            "latency-bound: per-chunk/prefill dispatch latency accounts "
            "for >=30% of an engine's wall clock, so quant ratios "
            "measure the axon tunnel, not the weight-stream win")
    return out


# ---------------------------------------------------------------------------
# fp8 train pilot: the hapi stepper's delayed-scaling fake-quant A/B
# (ISSUE 19).  Parity is the product — the loss envelope is the gate;
# the step-time ratio reports what the fake-quant costs where there is
# no fp8 hardware to pay it back.
# ---------------------------------------------------------------------------

def bench_fp8_train(B=16, steps=30, in_dim=64, width=256, depth=3,
                    out_dim=32, warmup=5, peak=1e12):
    """The same regression fit with and without
    ``amp_configs="fp8"`` (identical seeds/batches): reports steps/sec
    per mode, the loss-parity envelope (max relative deviation over
    the run; docs/kernels.md documents <= 5%), a flops-proxy MFU, and
    the delayed-scaling amax state's health."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    rng = np.random.RandomState(0)
    batches = [(rng.randn(B, in_dim).astype("float32"),
                rng.randn(B, out_dim).astype("float32"))
               for _ in range(steps)]

    def build(amp_configs=None):
        paddle.seed(3)
        layers = [nn.Linear(in_dim, width), nn.ReLU()]
        for _ in range(depth - 2):
            layers += [nn.Linear(width, width), nn.ReLU()]
        layers += [nn.Linear(width, out_dim)]
        net = nn.Sequential(*layers)
        m = paddle.Model(net,
                         inputs=[InputSpec([None, in_dim], "float32",
                                           "x")],
                         labels=[InputSpec([None, out_dim], "float32",
                                           "y")])
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        m.prepare(opt, nn.MSELoss(), amp_configs=amp_configs)
        return m

    def fit(m):
        losses, t_timed = [], None
        for i, (x, y) in enumerate(batches):
            if i == warmup:
                t_timed = time.perf_counter()
            res = m.train_batch([x], [y])
            loss = res[0] if isinstance(res, (tuple, list)) else res
            while isinstance(loss, (tuple, list, np.ndarray)):
                loss = loss[0]
            losses.append(float(loss))
        wall = time.perf_counter() - t_timed
        return losses, (steps - warmup) / wall

    base_losses, base_sps = fit(build())
    m8 = build(amp_configs="fp8")
    fp8_losses, fp8_sps = fit(m8)
    rel = [abs(a - b) / max(abs(a), 1e-6)
           for a, b in zip(base_losses, fp8_losses)]
    amax = np.asarray(m8._stepper.fp8_state)
    # flops proxy: fwd 2*B*W + bwd 4*B*W per step over the matmul params
    wparams = in_dim * width + (depth - 2) * width * width \
        + width * out_dim
    flops = 6.0 * B * wparams
    return {"steps_per_sec_base": round(base_sps, 2),
            "steps_per_sec_fp8": round(fp8_sps, 2),
            "fp8_step_overhead": round(base_sps / max(fp8_sps, 1e-9), 3),
            "mfu": round(flops * fp8_sps / peak, 6),
            "max_rel_loss_dev": round(max(rel), 4),
            "final_rel_loss_dev": round(rel[-1], 4),
            "loss_parity_ok": max(rel) < 0.05,
            "final_loss_base": round(base_losses[-1], 4),
            "final_loss_fp8": round(fp8_losses[-1], 4),
            "amax_entries": int(amax.size),
            "amax_finite": bool(np.isfinite(amax).all()),
            "steps": steps, "batch": B,
            "model": f"mlp_{in_dim}x{width}x{depth}"}


# ---------------------------------------------------------------------------
# Serving fleet: the SAME Poisson trace replayed through ONE engine and
# through N-replica ServingFleet routers (ISSUE 12).  Each replica is its
# own engine (slots + KV + compiled programs) stepped by its own thread.
# Every config runs in a FRESH SUBPROCESS whose CPU affinity is set to
# one core per replica-chip BEFORE jax initializes -- the chip-proxy
# discipline (PR 6's --xla_force_host_platform_device_count sibling):
# without it, XLA:CPU's machine-wide intra-op pool lets the single
# "one-chip" baseline borrow every core during prefill matmuls, which
# understates fleet scaling by exactly the borrowed factor.  Output is
# asserted BITWISE equal to the single engine per request (same seeds ->
# same weights in every child); the N=max child snapshots telemetry
# under the `router` tag (telemetry/router.{prom,jsonl} +
# router_requests.trace.json -- traces span router->replica).
# ---------------------------------------------------------------------------

_FLEET_CHILD_ENV = "BENCH_FLEET_CHILD"


def _fleet_run_config(P, n_replicas, snapshot=False):
    """One serving_fleet sub-config (runs inside the pinned child):
    ``n_replicas == 1`` is the plain single-engine baseline, else a
    ``ServingFleet`` with worker threads.  Returns plain-JSON results
    including every request's token ids (the parent's bitwise check)."""
    import jax  # noqa: F401  (device selection side effects)

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.router import ServingFleet
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    def bucket(n, lo):
        b = lo
        while b < n:
            b *= 2
        return b

    p_lo, p_hi = P["p_range"]
    n_lo, n_hi = P["n_range"]
    chunk = int(P["chunk"])
    max_seq = bucket(p_hi, p_lo) + bucket(n_hi, n_lo)
    # modest vocab ON PURPOSE: one replica's decode matmuls should fit
    # one proxy core the way one real replica fits one chip
    cfg = GPTConfig(vocab_size=P["vocab"], hidden_size=P["hidden"],
                    num_hidden_layers=P["layers"],
                    num_attention_heads=P["heads"],
                    max_position_embeddings=max_seq)
    paddle.seed(0)
    net = GPTForPretraining(cfg)
    net.eval()
    rng = np.random.RandomState(P["seed"])
    n_requests = int(P["n_requests"])
    plens = np.clip(
        rng.poisson(lam=rng.choice(P["p_lams"], size=n_requests)),
        p_lo, p_hi).astype(int)
    budgets = np.clip(
        rng.poisson(lam=rng.choice(P["n_lams"], size=n_requests)),
        n_lo, n_hi).astype(int)
    spl = int(P["sys_prompt_len"])
    sys_prompt = rng.randint(0, cfg.vocab_size, (spl,)).astype("int32")
    prompts = []
    for i, n in enumerate(plens):
        body = rng.randint(0, cfg.vocab_size, (int(n),)).astype("int32")
        if i % 2 == 0 and n > spl:
            body[:spl] = sys_prompt            # shared-prefix half
        prompts.append(body)

    def warm(eng):
        # compile every prefill bucket + the decode chunk once (the
        # timed pass then measures scheduling, not tracing)
        for b in eng.buckets:
            budget = min(chunk + 2, eng.MAX - b)
            if b <= p_hi * 2 and budget >= 1:
                eng.submit(np.ones((b,), np.int32), budget)
        eng.run()
        eng.reset()

    dtype = P.get("dtype", "float32")
    ekw = {"dtype": dtype}
    paged = P.get("paged") or {}
    if paged:
        ekw.update(kv_mode="paged", page_size=int(paged["page_size"]),
                   prefill_buckets=tuple(int(b)
                                         for b in paged["prefill_buckets"]))
        if paged.get("num_pages"):
            ekw["num_pages"] = int(paged["num_pages"])
    roles = P.get("roles")
    if n_replicas == 1:
        fe = ServingEngine(net, num_slots=P["slots"], chunk=chunk,
                           max_seq_len=max_seq, **ekw)
        warm(fe)
        reset = fe.reset
        run_trace = fe.run
        submit = fe.submit
    else:
        fl = ServingFleet(net, num_replicas=n_replicas,
                          num_slots=P["slots"], chunk=chunk,
                          max_seq_len=max_seq,
                          roles=tuple(roles) if roles else None,
                          handoff_ttl_s=float(P.get("handoff_ttl_s", 60.0)),
                          **ekw)
        for rep in fl.replicas:
            warm(rep.engine)
        if roles:
            # the per-engine warm bypassed the router: run a few real
            # requests through the fleet so the handoff path (budget-1
            # stub prefill + arm-at-k) is compiled before the clock
            for b in fl.replicas[0].engine.buckets:
                if b <= p_hi * 2:
                    fl.submit(np.ones((min(int(b), max_seq - n_lo),),
                                      np.int32), 2)
            fl.run(threads=True)
            fl.reset()
        reset = fl.reset
        run_trace = lambda: fl.run(threads=True)   # noqa: E731
        submit = fl.submit
    # best of `trials` timed passes (compiles amortized after warm):
    # the fleet walls are thread-scheduling-sensitive on the shared
    # cpu proxy, and the min is the capability estimate (the
    # chip_calibration discipline); outputs are asserted identical
    # across trials — noise may move the clock, never the tokens
    best = None
    for _ in range(int(P.get("trials", 2))):
        reset()
        try:
            # per-trial telemetry reset so the committed snapshot is
            # one-run-shaped (the last trial's), not a 2x aggregate
            from paddle_tpu import observability as _obs
            from paddle_tpu.framework import guardian as _guardian
            from paddle_tpu.observability import tracing as _tracing
            _obs.get_registry().reset()
            _tracing.reset()
            _guardian.clear_events()
        except Exception:
            pass
        t0 = time.perf_counter()
        reqs = [submit(p, int(b)) for p, b in zip(prompts, budgets)]
        run_trace()
        wall = time.perf_counter() - t0
        toks = [list(map(int, r.tokens)) for r in reqs]
        if best is not None:
            assert toks == best["toks"], "trial outputs diverged"
        if best is None or wall < best["wall"]:
            ttfts = sorted(r.ttft_ms for r in reqs)
            best = {"toks": toks, "wall": wall,
                    "ttfts": [round(r.ttft_ms, 2) for r in reqs],
                    "p99": ttfts[min(int(0.99 * (len(ttfts) - 1)),
                                     len(ttfts) - 1)]}
    if n_replicas == 1:
        extra = {"chunks": fe.stats["chunks"],
                 "prefills": fe.stats["prefills"]}
    else:
        extra = {"affinity_routes": fl.stats["affinity_routes"],
                 "least_loaded_routes":
                     fl.stats["least_loaded_routes"],
                 "rebalanced": fl.stats["rebalanced"],
                 "chunks": sum(r.engine.stats["chunks"]
                               for r in fl.replicas),
                 "prefills": sum(r.engine.stats["prefills"]
                                 for r in fl.replicas)}
        if roles:
            from paddle_tpu.framework import guardian
            hs = fl._handoff.snapshot()
            transfer_ms = sorted(
                e["transfer_ms"]
                for e in guardian.events("handoff_transfer"))
            extra.update(
                prefills_by_role={
                    r.role: r.engine.stats["prefills"]
                    for r in fl.replicas},
                handoff_transfers=hs["transfers"],
                handoff_fallbacks=hs["fallbacks"],
                mean_transfer_ms=round(
                    sum(transfer_ms) / len(transfer_ms), 2)
                if transfer_ms else None,
                p99_transfer_ms=round(
                    transfer_ms[min(int(0.99 * (len(transfer_ms) - 1)),
                                    len(transfer_ms) - 1)], 2)
                if transfer_ms else None)
            # the recompute-saved side of the TTFT attribution: what a
            # fallback would pay — one median prompt re-prefilled on the
            # (already-compiled) decode replica, timed directly
            dec = next(r.engine for r in fl.replicas
                       if r.role == "decode")
            probe = prompts[int(np.argsort(plens)[len(plens) // 2])]
            t0 = time.perf_counter()
            dec.submit(probe, 1)
            dec.run()
            extra["reprefill_probe_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
            dec.reset()
    useful = int(budgets.sum())
    out = {"tokens": best["toks"],
           "useful_tokens": useful,
           "useful_tokens_per_sec": round(useful / best["wall"], 1),
           "p99_ttft_ms": round(best["p99"], 1),
           "ttfts_ms": best["ttfts"], **extra}
    if snapshot:
        out["telemetry"] = _telemetry_snapshot(
            P.get("snapshot_tag", "router"))
    return out


def _fleet_child_main():
    """Child-process entry (``BENCH_FLEET_CHILD`` env): run one config
    in a fresh process (its own XLA pool + metrics registry — the
    telemetry snapshot a fleet child writes is that run's alone) and
    print one tagged JSON line.

    CPU affinity is set PROPORTIONALLY before jax initializes:
    ``cores_per_replica * n_replicas`` cores — every replica is backed
    by the same slice of hardware whatever the config, exactly like a
    real replica owning a chip.  Without it, XLA:CPU's machine-wide
    intra-op pool lets the "one-chip" baseline borrow every core
    during prefill matmuls (measured: 202-291 tok/s run-to-run on one
    machine), which both understates fleet scaling and makes the
    ratio noisy.  The trace runs fp32 ON PURPOSE: different affinity
    masks change XLA:CPU reduction partitioning, and at bf16 that
    flipped a near-tie greedy pick (one token in 5.5k) between masks —
    at fp32 the cross-config output is bitwise (asserted by the
    parent)."""
    spec = json.loads(os.environ[_FLEET_CHILD_ENV])
    n = int(spec["n_replicas"])
    cpr = int(spec.get("cores_per_replica") or 0)
    pinned = False
    if cpr > 0 and hasattr(os, "sched_setaffinity"):
        try:
            os.sched_setaffinity(
                0, set(range(min(cpr * n, os.cpu_count() or 1))))
            pinned = True
        except OSError:
            pass
    out = _fleet_run_config(spec["params"], n,
                            snapshot=spec.get("snapshot", False))
    out["pinned"] = pinned
    print("FLEET_CHILD_RESULT:" + json.dumps(out))


def bench_serving_fleet(n_requests=64, seed=0, hidden=256, layers=6,
                        heads=8, vocab=8192, p_range=(32, 224),
                        n_range=(32, 160), slots=4, chunk=64,
                        p_lams=(48, 96, 192), n_lams=(48, 96, 128),
                        replica_counts=(2, 4), sys_prompt_len=64):
    """Single engine (the baseline fleet-of-one) vs ``ServingFleet`` at
    each ``replica_counts`` entry, all over one Poisson-mixed trace
    submitted as a burst (every request queued at t=0 -- the regime
    where a deeper fleet drains the queue Nx faster, which is exactly
    what p99 TTFT measures).  Half the requests share a
    ``sys_prompt_len``-token system prompt so prefix-affinity routing
    has something to route on (dense engines here -- warmth effects are
    covered by the paged fleet tests; this config measures *scaling*).
    Each config runs in its own pinned subprocess (see the banner
    comment); useful-tok/s counts each request's own budget."""
    import subprocess
    import sys

    P = {"n_requests": n_requests, "seed": seed, "hidden": hidden,
         "layers": layers, "heads": heads, "vocab": vocab,
         "p_range": list(p_range), "n_range": list(n_range),
         "slots": slots, "chunk": chunk, "p_lams": list(p_lams),
         "n_lams": list(n_lams), "sys_prompt_len": sys_prompt_len}
    counts = [1] + [int(n) for n in replica_counts]
    # the even-division anchor: one replica-chip = ncpu / max-replicas
    # cores, for EVERY config (hardware scales with replica count the
    # way chips do in a real fleet)
    cores_per_replica = max(1, (os.cpu_count() or 1) // max(counts))
    results, base, telemetry, pinned = {}, None, None, True
    for n in counts:
        spec = {"n_replicas": n, "params": P,
                "cores_per_replica": cores_per_replica,
                "snapshot": n == max(counts)}
        env = dict(os.environ)
        env[_FLEET_CHILD_ENV] = json.dumps(spec)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=1800)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("FLEET_CHILD_RESULT:")]
        if proc.returncode != 0 or not line:
            raise RuntimeError(
                f"fleet child N={n} failed (rc={proc.returncode}): "
                f"{(proc.stderr or proc.stdout)[-400:]}")
        r = json.loads(line[-1][len("FLEET_CHILD_RESULT:"):])
        toks = r.pop("tokens")
        pinned &= bool(r.pop("pinned"))
        telemetry = r.pop("telemetry", telemetry)
        if n == 1:
            base = {"toks": toks,
                    "tps": r["useful_tokens_per_sec"],
                    "p99": r["p99_ttft_ms"],
                    "useful": r["useful_tokens"]}
        else:
            # the parity contract IS the product: bitwise or bust,
            # whatever replica/slot a request landed on
            assert toks == base["toks"], f"fleet N={n} output diverged"
            r["speedup_vs_one"] = round(
                r["useful_tokens_per_sec"] / max(base["tps"], 1e-9), 3)
            r["p99_ttft_vs_one"] = round(
                r["p99_ttft_ms"] / max(base["p99"], 1e-9), 3)
        r.pop("useful_tokens", None)
        r.pop("ttfts_ms", None)       # per-request detail: pd_split's
        results[str(n)] = r
    scaling_ok = all(results[str(n)]["speedup_vs_one"] >= 0.75 * n
                     for n in counts[1:])
    p99_ok = all(results[str(n)]["p99_ttft_ms"] < base["p99"]
                 for n in counts[1:])
    lat_ms = _dispatch_latency_ms()
    out = {"replicas": results,
           "speedup_n2": results.get("2", {}).get("speedup_vs_one"),
           "speedup_n4": results.get("4", {}).get("speedup_vs_one"),
           "bitwise": True,                 # asserted above, per fleet
           "scaling_near_linear": bool(scaling_ok),
           "p99_ttft_strictly_lower": bool(p99_ok),
           "requests": n_requests, "useful_tokens": base["useful"],
           "slots_per_replica": slots, "chunk": chunk,
           "dispatch_latency_ms": lat_ms,
           "cores_per_replica": cores_per_replica,
           "cpu_proxy_affinity": bool(pinned),
           "valid": bool(scaling_ok and p99_ok),
           "model": f"gpt_h{hidden}_l{layers}", "dtype": "float32",
           "note": ("burst-submitted Poisson trace, one subprocess "
                    "per config with PROPORTIONAL affinity (one "
                    "replica-chip = ncpu/max-replicas cores, set "
                    "before jax init — hardware scales with replica "
                    "count the way chips do; fp32 keeps cross-mask "
                    "greedy picks bitwise): replicas multiply the "
                    "slot pool and overlap dispatches; idle replicas "
                    "steal queued work from deep ones (router "
                    "rebalance), flattening the variable-budget "
                    "straggler tail.  Shared-host caveat: a replica "
                    "can transiently borrow sibling replicas' idle "
                    "cores through the child's one XLA pool, which "
                    "can push measured scaling slightly SUPER-linear "
                    "-- real chips cannot; read >=N as ~N")}
    if telemetry is not None:
        out["telemetry"] = telemetry
    if not out["valid"]:
        out["invalid_reason"] = (
            "fleet scaling below 0.75x-per-replica or p99 TTFT not "
            "strictly lower than the single engine -- the ratio is "
            "reported but should not be read as the fleet win")
    return out


def bench_prefill_decode_split(n_requests=32, seed=0, hidden=256,
                               layers=6, heads=8, vocab=8192,
                               p_range=(16, 96), n_range=(16, 64),
                               slots=4, chunk=16, page_size=16,
                               p_lams=(24, 48, 80), n_lams=(24, 48),
                               sys_prompt_len=16):
    """Disaggregated prefill/decode fleet (``roles=("prefill",
    "decode")``) vs the SAME 2-replica paged fleet unified, over one
    Poisson burst — both in pinned subprocesses like serving_fleet.
    The contract under measurement: every prompt prefills on the
    prefill replica only (``prefills_by_role["decode"] == 0``), its KV
    crosses as a checksummed bundle, and the output is BITWISE equal
    to the unified fleet.  TTFT attribution splits what the handoff
    costs (measured per-transfer wall, the `handoff_transfer` guardian
    events) from what it saves the decode replica (one median prompt
    re-prefilled there directly, the fallback price)."""
    import subprocess
    import sys

    def bucket(n, lo):
        b = lo
        while b < n:
            b *= 2
        return b

    buckets = []
    b = p_range[0]
    while b < bucket(p_range[1], p_range[0]) * 2:
        buckets.append(b)
        b *= 2
    # decode pool sized for the WHOLE admitted burst: every launched
    # handoff holds its page reservation until its decode slot frees,
    # and decode drains far slower than prefill — an undersized pool
    # turns the burst into reserve_timeout fallbacks (that ladder is
    # chaos-tested; this config measures the happy path)
    num_pages = n_requests * ((p_range[1] + n_range[1]) // page_size
                              + 2) + 1
    P = {"n_requests": n_requests, "seed": seed, "hidden": hidden,
         "layers": layers, "heads": heads, "vocab": vocab,
         "p_range": list(p_range), "n_range": list(n_range),
         "slots": slots, "chunk": chunk, "p_lams": list(p_lams),
         "n_lams": list(n_lams), "sys_prompt_len": sys_prompt_len,
         "paged": {"page_size": page_size, "prefill_buckets": buckets,
                   "num_pages": num_pages},
         "snapshot_tag": "pd_split"}
    cores_per_replica = max(1, (os.cpu_count() or 1) // 2)
    results, telemetry = {}, None
    for name, roles in (("unified", None),
                        ("split", ["prefill", "decode"])):
        spec = {"n_replicas": 2,
                "params": {**P, "roles": roles},
                "cores_per_replica": cores_per_replica,
                "snapshot": roles is not None}
        env = dict(os.environ)
        env[_FLEET_CHILD_ENV] = json.dumps(spec)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=1800)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("FLEET_CHILD_RESULT:")]
        if proc.returncode != 0 or not line:
            raise RuntimeError(
                f"pd_split child {name} failed (rc={proc.returncode}): "
                f"{(proc.stderr or proc.stdout)[-400:]}")
        r = json.loads(line[-1][len("FLEET_CHILD_RESULT:"):])
        r.pop("pinned", None)
        telemetry = r.pop("telemetry", telemetry)
        results[name] = r
    uni, spl = results["unified"], results["split"]
    # the parity contract IS the product: same tokens whether the KV
    # was computed in place or crossed replicas as a bundle
    bitwise = uni.pop("tokens") == spl.pop("tokens")
    uni_ttfts = uni.pop("ttfts_ms")
    spl_ttfts = spl.pop("ttfts_ms")
    mean = lambda xs: round(sum(xs) / len(xs), 2)   # noqa: E731
    attribution = {
        "mean_ttft_unified_ms": mean(uni_ttfts),
        "mean_ttft_split_ms": mean(spl_ttfts),
        "mean_transfer_ms": spl.get("mean_transfer_ms"),
        "p99_transfer_ms": spl.get("p99_transfer_ms"),
        "transfer_share_of_ttft": round(
            spl["mean_transfer_ms"] / max(mean(spl_ttfts), 1e-9), 3)
        if spl.get("mean_transfer_ms") else None,
        "reprefill_saved_ms": spl.get("reprefill_probe_ms"),
    }
    decode_prefills = spl.get("prefills_by_role", {}).get("decode")
    valid = bool(bitwise and decode_prefills == 0
                 and spl.get("handoff_fallbacks") == 0
                 and spl.get("handoff_transfers") == n_requests)
    out = {"unified": uni, "split": spl, "bitwise": bitwise,
           "decode_prompt_prefills": decode_prefills,
           "ttft_attribution": attribution,
           "requests": n_requests, "slots_per_replica": slots,
           "chunk": chunk, "page_size": page_size,
           "cores_per_replica": cores_per_replica,
           "valid": valid,
           "model": f"gpt_h{hidden}_l{layers}", "dtype": "float32",
           "note": ("same Poisson burst through a unified 2-replica "
                    "paged fleet and the same fleet split "
                    "prefill/decode; both pinned like serving_fleet.  "
                    "The split config serializes all prompt prefills "
                    "on ONE replica, so burst p99 TTFT is expected to "
                    "trail the unified fleet on this proxy -- the win "
                    "disaggregation buys (decode batches never stall "
                    "behind a prompt prefill) shows as the decode "
                    "replica's zero prompt prefills and in the "
                    "attribution: a bundle import costs "
                    "mean_transfer_ms where the fallback "
                    "(re-prefill on the decode replica) costs "
                    "reprefill_saved_ms")}
    if telemetry is not None:
        out["telemetry"] = telemetry
    if not valid:
        out["invalid_reason"] = (
            "expected bitwise output, zero decode prompt prefills, "
            "zero fallbacks and one transfer per request")
    return out


# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# GPT-MoE: GShard-pattern sparse FFNs (every other layer 8-expert top-2),
# single chip.  MFU is computed over ACTIVE FLOPs (top_k of E experts per
# token), the standard sparse-model accounting.
# ---------------------------------------------------------------------------

def bench_gpt_moe(B=12, S=1024, iters=6, peak=197e12):
    # B sweep (r5, scanned): 8 -> 76.2k tok/s (37.6%), 12 -> 77.8k
    # (38.5%), 16 -> 76.0k (37.5%); capacity-bucket padding waste peaks
    # at small B, HBM pressure at large
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.models import GPTMoEForPretraining, gpt_moe_small

    cfg = gpt_moe_small(vocab_size=50304)
    paddle.seed(0)
    net = GPTMoEForPretraining(cfg)
    net.eval()
    params = [p for _, p in net.named_parameters()]
    pvals = [p._value for p in params]
    moes = net.gpt.moe_layers()

    def loss_fn(pv, ids, labels):
        from paddle_tpu.ops.pallas.fused_xent import fused_softmax_xent
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v.astype(jnp.bfloat16) \
                if jnp.issubdtype(v.dtype, jnp.floating) else v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                logits = net(paddle.Tensor(ids))._value
                aux = net.aux_loss()._value
        finally:
            for p, v in zip(params, olds):
                p._value = v
        Bv, Sv, V = logits.shape
        lb = jnp.concatenate([labels[:, 1:],
                              jnp.full((Bv, 1), -1, labels.dtype)], 1)
        row = fused_softmax_xent(logits.reshape(Bv * Sv, V),
                                 lb.reshape(-1).astype(jnp.int32))
        ce = jnp.sum(row) / (Bv * (Sv - 1))
        return ce + cfg.aux_loss_weight * aux.astype(jnp.float32)

    b1, b2, eps, lr, wd = 0.9, 0.95, 1e-8, 1e-4, 0.01

    def step(pv, m, v, t, ids, labels):
        loss, g = jax.value_and_grad(loss_fn)(pv, ids, labels)
        t = t + 1
        new_p, new_m, new_v = [], [], []
        for p, gi, mi, vi in zip(pv, g, m, v):
            nmi = b1 * mi + (1 - b1) * gi
            nvi = b2 * vi + (1 - b2) * gi * gi
            np_ = p - lr * ((nmi / (1 - b1 ** t)) /
                            (jnp.sqrt(nvi / (1 - b2 ** t)) + eps) + wd * p)
            new_p.append(np_)
            new_m.append(nmi)
            new_v.append(nvi)
        return loss, new_p, new_m, new_v, t

    K = int(os.environ.get("BENCH_STEPS_PER_CALL", "5"))

    def scan_steps(pv, m, v, t, ids, labels):
        def body(carry, _):
            pv, m, v, t = carry
            loss, pv, m, v, t = step(pv, m, v, t, ids, labels)
            return (pv, m, v, t), loss
        (pv, m, v, t), losses = jax.lax.scan(
            body, (pv, m, v, t), None, length=K)
        return losses[-1], pv, m, v, t

    step_jit = jax.jit(scan_steps, donate_argnums=(0, 1, 2))
    m0 = [jnp.zeros_like(v) for v in pvals]
    v0 = [jnp.zeros_like(v) for v in pvals]
    t0 = jnp.zeros((), jnp.int32)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (B, S)).astype("int32"))

    def run(pv, m, v, t):
        loss, pv, m, v, t = step_jit(pv, m, v, t, ids, ids)
        return loss, pv, m, v, t

    loss, pvals, m0, v0, t0 = run(pvals, m0, v0, t0)
    _readback_sync(loss)
    dt, final_loss, _ = _timeit(run, iters, pvals, m0, v0, t0)
    tokens_per_sec = iters * K * B * S / dt

    n_params = sum(int(np.prod(p.shape)) for p in params)
    expert_params = sum(
        int(np.prod(getattr(m, nm).shape))
        for m in moes for nm in ("expert_w1", "expert_b1",
                                 "expert_w2", "expert_b2"))
    active = n_params - expert_params * (1 - cfg.top_k / cfg.num_experts)
    fpt = 6 * active + 6 * cfg.num_hidden_layers * S * cfg.hidden_size
    return {"tokens_per_sec": round(tokens_per_sec, 1),
            "active_mfu": round(tokens_per_sec * fpt / peak, 4),
            "loss": round(final_loss, 4), "params": n_params,
            "active_params": int(active),
            "num_experts": cfg.num_experts, "top_k": cfg.top_k,
            "moe_layers": len(moes), "batch": B, "seq": S}


def main():
    import jax

    from paddle_tpu.models import GPTConfig

    on_tpu = jax.default_backend() not in ("cpu",)
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    which = os.environ.get("BENCH_CONFIGS", "").split(",") \
        if os.environ.get("BENCH_CONFIGS") else None
    # extras stop launching once the budget is spent so the primary JSON
    # line always lands inside the driver's window (compiles through the
    # axon tunnel cost ~3-4 min per config)
    budget_s = float(os.environ.get("BENCH_TIME_BUDGET_S", "1500"))
    start = time.perf_counter()

    def want(name, result_key=None):
        named = (which is None or name in which
                 or (result_key is not None and result_key in which))
        if not named:
            return False
        if name != "gpt125m" and time.perf_counter() - start > budget_s:
            configs[result_key or name] = {
                "skipped": "BENCH_TIME_BUDGET_S exhausted"}
            return False
        return True

    configs = {}
    telemetry = {}
    primary = None
    kernel_measured = {}
    metric = "gpt125m_train_tokens_per_sec_per_chip"
    if on_tpu:
        try:
            # chip-health reference: bare-matmul fraction of peak (see
            # chip_calibration docstring; degraded tunnel sessions make
            # every MFU below scale down with this number)
            configs["chip_calibration"] = chip_calibration()
        except Exception as e:
            configs["chip_calibration"] = repr(e)[:120]
        gpt125 = GPTConfig(vocab_size=50304, hidden_size=768,
                           num_hidden_layers=12, num_attention_heads=12,
                           max_position_embeddings=1024)
        # B=24: best measured single-chip throughput with the fused-CE
        # loss (B=16: 39%, B=24: 42.3%, B=28: 40.2%, B=32: 38.7% —
        # larger batches start spilling on the bf16 logits + bwd)
        if want("gpt125m"):
            primary = bench_gpt(gpt125, B=24, S=1024, iters=20, peak=peak)
            telemetry["train"] = _telemetry_snapshot("train")
        if want("gpt350m"):
            try:
                gpt350 = GPTConfig(
                    vocab_size=50304, hidden_size=1024,
                    num_hidden_layers=24, num_attention_heads=16,
                    max_position_embeddings=1024)
                configs["gpt350m"] = bench_gpt(gpt350, B=8, S=1024,
                                               iters=10, peak=peak)
            except Exception as e:
                configs["gpt350m"] = {"error": repr(e)[:200]}
        if want("resnet50"):
            try:
                configs["resnet50"] = bench_resnet50(B=256, iters=10)
            except Exception as e:
                configs["resnet50"] = {"error": repr(e)[:200]}
        if want("bert", "bert_base_amp"):
            try:
                # B sweep (r3): 16→36.0%, 32→37.9%, 48→41.2%, 64→38.2%
                # (the MLM logits block tops out VMEM-friendly at 48);
                # r4 scanned re-check: 48→43.4%, 64→40.6%, 96→38.0%.
                #
                # Why BERT sits at ~43% (latency-free r4 analysis, the
                # VERDICT #2 "residual is physics" note): the bidir
                # flash kernels are VPU-transcendental-bound, not
                # schedule-bound — EVERY (hb, bq, bk) config measures
                # fwd 2.5-2.8ms / bwd 2.9-3.4ms per layer on 50-call
                # latency-free chains (7-17% of MXU peak; attention is
                # 8% of credited FLOPs but ~30% of wall). The XLA
                # dense path is 1.27-1.37x SLOWER at this shape, so
                # flash is the right call. Ablations: stubbing
                # attention or the MLM head moves the step <5% each;
                # the non-attention remainder runs at ~85% matmul
                # efficiency. A microbench-winning config (256,512,
                # hb=8) collapsed the FULL model to 11% MFU (VMEM
                # pressure beside live model buffers) — kernel tables
                # must be validated at model level.
                configs["bert_base_amp"] = bench_bert(B=48, S=512,
                                                      iters=10, peak=peak)
            except Exception as e:
                configs["bert_base_amp"] = {"error": repr(e)[:200]}
        if want("longctx", "gpt125m_s4096"):
            try:
                gptlc = GPTConfig(
                    vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    max_position_embeddings=4096)
                # r4 scanned-bench B sweep: B=6 45.4%, 4 46.0%, 3 46.1%,
                # 2 46.7%, 1 43.4% — smaller per-step HBM live set wins
                # until B=1 under-fills the MXU.
                #
                # Why ~47% is the ceiling at S=4096 (r5 physics note,
                # VERDICT r4 #3; latency-subtracted tensor-carry chains,
                # tools/s4096_analysis.py — beware: scalar-carry chains
                # get their matmul hoisted by XLA's c*(A@B) rewrite and
                # read >100% of peak):
                #   step = 87.6 ms (B=2, 8192 tok, 46.9% MFU).  Budget:
                #   - flash attention f+b: 3.12 ms/layer x 12 = 37.4 ms
                #     = 43% of wall at 29% of MXU peak, carrying only
                #     23% of credited FLOPs.  fwd alone 1.05 ms (25%).
                #     Same class as the BERT note: VPU/exp-bound, not
                #     schedule-bound — the (bq, bk) landscape re-swept
                #     at S=4096 is flat (512/1024/2048 combos: 46.3,
                #     46.9, 46.9, 46.9%), dense attention is 11x slower
                #     (11.6 ms fwd), and remat is off so fwd is paid
                #     once.
                #   - lm head + fused xent f+b: 11.4 ms at 84% of peak
                #     (50304-wide streaming, near its HBM roofline).
                #   - proj+MLP matmuls reach 95% of peak in isolation;
                #     the remaining 38.8 ms of layer-remainder (norms,
                #     residual/cast traffic, AdamW's ~4 ms HBM sweep of
                #     124M fp32 m/v/p) averages 55%.
                #   With attention pinned at its measured floor and
                #   every other component at its best measured
                #   efficiency, the step bottoms at ~75 ms = ~53% MFU;
                #   the 47->53 gap is the remainder's backward (55% vs
                #   95% isolated), the same VPU-bound fused-norm + cast
                #   overheads quantified in the BERT note below.  48%+
                #   needs a faster flash-bwd class (e.g. fusing the
                #   exp recompute differently), not block tuning.
                configs["gpt125m_s4096"] = bench_gpt(gptlc, B=2, S=4096,
                                                     iters=10, peak=peak)
            except Exception as e:
                configs["gpt125m_s4096"] = {"error": repr(e)[:200]}
        if want("longctx_remat", "gpt125m_s4096_remat"):
            try:
                # selective remat (dots_saveable keeps matmul outputs,
                # recomputes norms/elementwise) frees activation HBM so
                # the batch can grow past the B=2 operating point the
                # no-remat sweep topped out at (0.468 MFU) — report the
                # MFU delta against the plain config alongside
                gptlcr = GPTConfig(
                    vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    max_position_embeddings=4096,
                    remat_policy="dots_saveable")
                r = bench_gpt(gptlcr, B=8, S=4096, iters=10, peak=peak)
                base = configs.get("gpt125m_s4096") or {}
                if isinstance(base, dict) and base.get("mfu"):
                    r["mfu_delta_vs_no_remat"] = round(
                        r["mfu"] - base["mfu"], 4)
                r["remat_policy"] = "dots_saveable"
                configs["gpt125m_s4096_remat"] = r
            except Exception as e:
                configs["gpt125m_s4096_remat"] = {"error": repr(e)[:200]}
        if want("longctx_sweep", "gpt125m_s4096_sweep"):
            try:
                configs["gpt125m_s4096_sweep"] = bench_longctx_sweep(
                    peak, on_tpu=True)
            except Exception as e:
                configs["gpt125m_s4096_sweep"] = {"error": repr(e)[:200]}
        if want("kernels", "kernel_probe"):
            try:
                kp = bench_kernel_probe(on_tpu=True)
                kernel_measured.update(kp.pop("measured", {}))
                configs["kernel_probe"] = kp
            except Exception as e:
                configs["kernel_probe"] = {"error": repr(e)[:200]}
        if want("gpt1p3b", "gpt1p3b_hybrid"):
            try:
                configs["gpt1p3b_hybrid"] = bench_gpt1p3b_hybrid(peak=peak)
            except Exception as e:
                configs["gpt1p3b_hybrid"] = {"error": repr(e)[:200]}
        if want("eager", "eager_overhead"):
            try:
                configs["eager_overhead"] = bench_eager_overhead()
            except Exception as e:
                configs["eager_overhead"] = {"error": repr(e)[:200]}
        if want("fp8", "fp8_linear"):
            try:
                configs["fp8_linear"] = bench_fp8_linear()
            except Exception as e:
                configs["fp8_linear"] = {"error": repr(e)[:200]}
        # decode before gpt_moe: in a full run gpt_moe ends near the time
        # budget and whatever follows it risks a budget skip
        if want("decode"):
            try:
                configs["decode"] = bench_decode()
            except Exception as e:
                configs["decode"] = {"error": repr(e)[:200]}
        if want("serving"):
            try:
                configs["serving"] = bench_serving()
            except Exception as e:
                configs["serving"] = {"error": repr(e)[:200]}
            telemetry["serving"] = _telemetry_snapshot("serving")
        if want("serving_prefix"):
            try:
                configs["serving_prefix"] = bench_serving_prefix()
            except Exception as e:
                configs["serving_prefix"] = {"error": repr(e)[:200]}
            telemetry["serving_prefix"] = _telemetry_snapshot("serving_prefix")
        if want("serving_spec"):
            try:
                configs["serving_spec"] = bench_serving_spec()
            except Exception as e:
                configs["serving_spec"] = {"error": repr(e)[:200]}
            telemetry["serving_spec"] = _telemetry_snapshot("serving_spec")
        if want("serving_quant"):
            try:
                configs["serving_quant"] = bench_serving_quant()
            except Exception as e:
                configs["serving_quant"] = {"error": repr(e)[:200]}
            telemetry["serving_quant"] = _telemetry_snapshot("serving_quant")
        if want("fp8_train"):
            try:
                configs["fp8_train"] = bench_fp8_train(peak=peak)
            except Exception as e:
                configs["fp8_train"] = {"error": repr(e)[:200]}
            telemetry["fp8_train"] = _telemetry_snapshot("fp8_train")
        if want("serving_fleet"):
            try:
                configs["serving_fleet"] = bench_serving_fleet()
            except Exception as e:
                configs["serving_fleet"] = {"error": repr(e)[:200]}
            # the pinned N=max CHILD wrote the router telemetry
            # snapshot (its registry holds the fleet run, ours is
            # empty) — surface its paths instead of overwriting
            telemetry["router"] = configs["serving_fleet"].pop(
                "telemetry", {"skipped": "fleet child did not report"})
        if want("prefill_decode_split"):
            try:
                configs["prefill_decode_split"] = \
                    bench_prefill_decode_split()
            except Exception as e:
                configs["prefill_decode_split"] = {"error": repr(e)[:200]}
            telemetry["pd_split"] = configs["prefill_decode_split"].pop(
                "telemetry", {"skipped": "pd_split child did not report"})
        if want("moe", "gpt_moe"):
            try:
                configs["gpt_moe"] = bench_gpt_moe(peak=peak)
            except Exception as e:
                configs["gpt_moe"] = {"error": repr(e)[:200]}
    else:
        tiny = GPTConfig(vocab_size=1024, hidden_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         max_position_embeddings=256)
        primary = bench_gpt(tiny, B=2, S=128, iters=5, peak=peak)
        telemetry["train"] = _telemetry_snapshot("train")
        metric = "gpt_tiny_cpu_proxy_tokens_per_sec"
        if which is not None and "serving" in which:
            try:
                configs["serving"] = bench_serving(
                    n_requests=8, hidden=64, layers=2, heads=2,
                    p_range=(8, 32), n_range=(4, 16), slots=4, chunk=8,
                    p_lams=(12, 24), n_lams=(6, 12))
            except Exception as e:
                configs["serving"] = {"error": repr(e)[:200]}
            telemetry["serving"] = _telemetry_snapshot("serving")
        if which is not None and "serving_prefix" in which:
            try:
                configs["serving_prefix"] = bench_serving_prefix(
                    n_requests=8, hidden=64, layers=2, heads=2,
                    sys_len=32, sfx_range=(4, 12), n_range=(4, 12),
                    slots=4, chunk=8, page_size=8)
            except Exception as e:
                configs["serving_prefix"] = {"error": repr(e)[:200]}
            telemetry["serving_prefix"] = _telemetry_snapshot("serving_prefix")
        if which is not None and "serving_spec" in which:
            try:
                # decode-heavy trace on a weight-stream-bound proxy
                # (h=128 with the 50304-wide head): a gamma+1-wide
                # verify costs near one narrow step, the same fixed-
                # cost-amortization physics as the TPU dispatch story
                # (measured 2.0x dense / 2.0x paged at 0.59 acceptance)
                configs["serving_spec"] = bench_serving_spec(
                    n_requests=12, hidden=128, layers=2, heads=2,
                    p_range=(8, 16), n_range=(48, 96), slots=4, chunk=8,
                    gamma=6, ngram=2, page_size=8,
                    p_lams=(8, 12), n_lams=(64, 80))
            except Exception as e:
                configs["serving_spec"] = {"error": repr(e)[:200]}
            telemetry["serving_spec"] = _telemetry_snapshot("serving_spec")
        if which is not None and "serving_quant" in which:
            try:
                # decode-heavy, weight-stream-bound proxy: h=512 puts
                # the 50304-wide fp32 head at 103MB — DRAM-resident, so
                # the tiled int8 lowering's 4x byte cut is a measured
                # win on the CPU backend too (1.4-1.6x at decode
                # M=slots); fp8's e4m3 upconvert is software-emulated
                # off-TPU, so its column reads ~1.0x here and the
                # deploy truth is the kernel_uplift_v5e cross-ref
                configs["serving_quant"] = bench_serving_quant(
                    n_requests=12, hidden=512, layers=2, heads=4,
                    p_range=(8, 16), n_range=(24, 48), slots=8, chunk=8,
                    dtype="float32", p_lams=(8, 12), n_lams=(28, 40))
            except Exception as e:
                configs["serving_quant"] = {"error": repr(e)[:200]}
            telemetry["serving_quant"] = _telemetry_snapshot("serving_quant")
        if which is not None and "fp8_train" in which:
            try:
                configs["fp8_train"] = bench_fp8_train(peak=peak)
            except Exception as e:
                configs["fp8_train"] = {"error": repr(e)[:200]}
            telemetry["fp8_train"] = _telemetry_snapshot("fp8_train")
        if which is not None and "serving_fleet" in which:
            try:
                configs["serving_fleet"] = bench_serving_fleet()
            except Exception as e:
                configs["serving_fleet"] = {"error": repr(e)[:200]}
            # the pinned N=max CHILD wrote the router telemetry
            # snapshot (its registry holds the fleet run, ours is
            # empty) — surface its paths instead of overwriting
            telemetry["router"] = configs["serving_fleet"].pop(
                "telemetry", {"skipped": "fleet child did not report"})
        if which is not None and "prefill_decode_split" in which:
            try:
                configs["prefill_decode_split"] = \
                    bench_prefill_decode_split()
            except Exception as e:
                configs["prefill_decode_split"] = {"error": repr(e)[:200]}
            telemetry["pd_split"] = configs["prefill_decode_split"].pop(
                "telemetry", {"skipped": "pd_split child did not report"})
        if which is not None and \
                {"longctx_sweep", "gpt125m_s4096_sweep"} & set(which):
            try:
                configs["gpt125m_s4096_sweep"] = bench_longctx_sweep(
                    peak, on_tpu=False)
            except Exception as e:
                configs["gpt125m_s4096_sweep"] = {"error": repr(e)[:200]}
        if which is not None and \
                {"kernels", "kernel_probe"} & set(which):
            try:
                kp = bench_kernel_probe(on_tpu=False)
                kernel_measured.update(kp.pop("measured", {}))
                configs["kernel_probe"] = kp
            except Exception as e:
                configs["kernel_probe"] = {"error": repr(e)[:200]}
        if which is not None and \
                {"gpt1p3b", "gpt1p3b_hybrid"} & set(which):
            # 1 visible device -> bench_gpt1p3b_hybrid re-execs itself
            # onto the simulated 8-device mesh (cpu_proxy result)
            try:
                configs["gpt1p3b_hybrid"] = bench_gpt1p3b_hybrid(peak=peak)
            except Exception as e:
                configs["gpt1p3b_hybrid"] = {"error": repr(e)[:200]}

    # roofline/MFU-attribution artifact: join every surface the run
    # compiled (train stepper + any serving engines) with the measured
    # per-dispatch latency.  HBM bandwidth: v5e ~819 GB/s; the CPU
    # proxy gets a nominal figure (the table still shows analytical
    # intensity + compute/memory split — attribution fractions are
    # proxy-scale there and labeled by the peak used).
    hbm_bw = 819e9 if on_tpu else 50e9
    measured = dict(kernel_measured)   # kernel_probe latency-clean rows
    if primary is not None and isinstance(primary, dict) and \
            primary.get("dispatch_ms"):
        measured["bench.train_step"] = primary["dispatch_ms"]
    telemetry["roofline"] = _roofline_snapshot(measured, peak, hbm_bw)
    telemetry["memory"] = _memory_snapshot()

    if primary is not None:
        rate = primary["tokens_per_sec"]
    else:
        # BENCH_CONFIGS excluded gpt125m: promote the first config that
        # produced a throughput number, labeled by its own name
        for name, cfg in configs.items():
            if not isinstance(cfg, dict):
                continue
            for key in ("tokens_per_sec", "images_per_sec",
                        "decode_tokens_per_sec"):
                if cfg.get(key):
                    metric = f"{name}_{key}"
                    rate = cfg[key]
                    primary = cfg
                    break
            if primary is not None:
                break
        else:
            raise SystemExit("no benchmark config produced a number: "
                             + json.dumps(configs))
    print(json.dumps({
        "metric": metric,
        "value": rate,
        "unit": "tokens/sec" if "tokens" in metric else "images/sec",
        "vs_baseline": 1.0,
        "extra": {**primary, "configs": configs,
                  "telemetry": telemetry},
    }))


if __name__ == "__main__":
    import sys

    if "--hybrid-cpu-proxy" in sys.argv[1:]:
        _hybrid_cpu_proxy_child()
    elif _FLEET_CHILD_ENV in os.environ:
        _fleet_child_main()
    else:
        main()
