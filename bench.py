"""Benchmark runner — prints ONE JSON line for the driver.

Round 1 metric: LeNet-MNIST Model.fit throughput on the local chip
(BASELINE config #1); later rounds switch to GPT-1.3B tokens/sec/chip.
vs_baseline is vs. BASELINE.json's published numbers — none exist
(published: {}), so it reports 1.0 when the run completes at sane speed.
"""
import json
import time

import numpy as np


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.metric import Accuracy
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    model = paddle.Model(net, inputs=[InputSpec([None, 1, 28, 28],
                                                "float32", "image")],
                         labels=[InputSpec([None, 1], "int64", "label")])
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())

    bs = 512
    x = np.random.rand(bs, 1, 28, 28).astype("float32")
    y = np.random.randint(0, 10, (bs, 1)).astype("int64")
    # warmup/compile
    model.train_batch([x], [y])
    n = 30
    t0 = time.perf_counter()
    for _ in range(n):
        model.train_batch([x], [y])
    dt = time.perf_counter() - t0
    ips = n * bs / dt
    print(json.dumps({
        "metric": "lenet_mnist_train_images_per_sec",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
