"""Benchmark runner — prints ONE JSON line for the driver.

Metric: GPT (125M-class) training throughput in tokens/sec/chip on the
local device — fused fwd+bwd+AdamW in one jitted executable, bf16 compute
with fp32 master params (the BASELINE GPT workload scaled to one chip;
later rounds add the 1.3B multi-chip config).  vs_baseline is 1.0 when the
run completes (BASELINE.json publishes no reference numbers).
"""
import json
import math
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework import autograd as _ag
    from paddle_tpu.framework.random import rng_scope
    from paddle_tpu.models import GPTConfig, GPTForPretraining

    paddle.seed(0)
    on_tpu = jax.default_backend() not in ("cpu",)
    # 125M-class on the chip; tiny proxy on CPU so the bench always runs
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=1024)
        # B=16 is the measured v5e sweet spot (B=8: 31%, B=16: 36.5% MFU)
        B, S, iters = 16, 1024, 20
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=256)
        B, S, iters = 2, 128, 5

    net = GPTForPretraining(cfg)
    net.eval()  # dropout off (probs are 0.0 anyway)
    params = [p for _, p in net.named_parameters()]
    pvals = [p._value for p in params]

    def forward_pure(pv, ids):
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                return net(paddle.Tensor(ids))._value
        finally:
            for p, v in zip(params, olds):
                p._value = v

    def loss_fn(pv, ids, labels):
        compute = [v.astype(jnp.bfloat16)
                   if jnp.issubdtype(v.dtype, jnp.floating) else v
                   for v in pv]
        logits = forward_pure(compute, ids).astype(jnp.float32)
        V = logits.shape[-1]
        lg = logits[:, :-1, :].reshape(-1, V)
        lb = labels[:, 1:].reshape(-1)
        logp = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.take_along_axis(logp, lb[:, None], 1).mean()

    b1, b2, eps, lr, wd = 0.9, 0.95, 1e-8, 1e-4, 0.01

    def step(pv, m, v, t, ids, labels):
        loss, g = jax.value_and_grad(loss_fn)(pv, ids, labels)
        t = t + 1
        new_p, new_m, new_v = [], [], []
        for p, gi, mi, vi in zip(pv, g, m, v):
            nmi = b1 * mi + (1 - b1) * gi
            nvi = b2 * vi + (1 - b2) * gi * gi
            mhat = nmi / (1 - b1 ** t)
            vhat = nvi / (1 - b2 ** t)
            np_ = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
            new_p.append(np_)
            new_m.append(nmi)
            new_v.append(nvi)
        return loss, new_p, new_m, new_v, t

    step_jit = jax.jit(step, donate_argnums=(0, 1, 2))
    m0 = [jnp.zeros_like(v) for v in pvals]
    v0 = [jnp.zeros_like(v) for v in pvals]
    t0 = jnp.zeros((), jnp.int32)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))

    loss, pvals, m0, v0, t0 = step_jit(pvals, m0, v0, t0, ids, ids)
    # IMPORTANT: sync via host readback — through the axon PJRT tunnel,
    # block_until_ready() returns before execution finishes, inflating
    # throughput ~70x; float() forces a D2H of the final value, which is a
    # true completion barrier on the whole dependency chain.
    float(loss)  # compile + warmup
    t_start = time.perf_counter()
    for _ in range(iters):
        loss, pvals, m0, v0, t0 = step_jit(pvals, m0, v0, t0, ids, ids)
    final_loss = float(loss)
    dt = time.perf_counter() - t_start
    tokens_per_sec = iters * B * S / dt

    n_params = sum(int(np.prod(v.shape)) for v in pvals)
    flops_per_tok = 6 * n_params
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_tok / peak

    print(json.dumps({
        "metric": "gpt125m_train_tokens_per_sec_per_chip" if on_tpu
                  else "gpt_tiny_cpu_proxy_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "extra": {"loss": round(final_loss, 4), "mfu": round(mfu, 4),
                  "params": n_params, "batch": B, "seq": S},
    }))


if __name__ == "__main__":
    main()
