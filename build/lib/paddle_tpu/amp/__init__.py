"""AMP (reference: python/paddle/amp/{auto_cast,grad_scaler}.py).

TPU-native: bf16 is the native mixed-precision dtype and needs no loss
scaling, so ``auto_cast`` is a dtype-policy context consulted by the op
layer, and ``GradScaler`` keeps the reference's API surface but defaults to
a no-op for bf16 (dynamic scaling still implemented for fp16 parity).
"""
from contextlib import contextmanager

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import dtypes

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "is_auto_cast_enabled", "get_amp_dtype"]

_AMP_STATE = {"enabled": False, "dtype": jnp.bfloat16, "level": "O1"}

# Ops whitelisted for low precision under O1 (matmul-class only, mirroring
# the reference's white list in paddle/fluid/eager/amp_utils).
WHITE_LIST = {"matmul", "conv2d", "einsum", "linear"}
BLACK_LIST = {"log", "exp", "softmax", "cross_entropy", "mean", "sum",
              "norm", "layer_norm", "batch_norm"}


def is_auto_cast_enabled():
    return _AMP_STATE["enabled"]


def get_amp_dtype():
    return _AMP_STATE["dtype"] if _AMP_STATE["enabled"] else None


def get_amp_level():
    return _AMP_STATE["level"]


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = dict(_AMP_STATE)
    _AMP_STATE["enabled"] = enable
    _AMP_STATE["dtype"] = dtypes.convert_dtype(dtype)
    _AMP_STATE["level"] = level
    try:
        yield
    finally:
        _AMP_STATE.update(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision (master weights kept by the
    optimizer when multi_precision=True)."""
    d = dtypes.convert_dtype(dtype)

    def _cast_model(m):
        for p in m.parameters():
            if dtypes.is_floating_dtype(p._value.dtype):
                p._master = p._value  # fp32 master copy
                p._value = p._value.astype(d)
        return m
    if level == "O2":
        if isinstance(models, (list, tuple)):
            models = type(models)(_cast_model(m) for m in models)
        else:
            models = _cast_model(models)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (no-op by default on TPU/bf16; full dynamic
    scaling for fp16 parity with the reference's GradScaler)."""

    def __init__(self, enable=True, init_loss_scaling=2.**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p._grad is not None:
                g = p._grad * inv
                finite = bool(jnp.all(jnp.isfinite(g)))
                if not finite:
                    found = True
                p._grad = g
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        """Unscale + conditionally step.  Does NOT update the scale —
        call ``update()`` after (reference GradScaler contract)."""
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        self._unscaled = False
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
