// Bounded blocking byte-buffer queue: the C++ core of the DataLoader
// prefetch pipeline (reference: the reader blocking queue under
// paddle/fluid/operators/reader/ + LoDTensorBlockingQueueHolder that the
// Python DataLoader feeds).  Worker processes produce batches; a
// collector pushes them here; the training loop pops.  The bounded
// capacity is the `prefetch_factor` backpressure.

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

#include "common.h"

namespace {

struct BlockingQueue {
  size_t capacity;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<std::string> items;
  bool closed = false;

  explicit BlockingQueue(size_t cap) : capacity(cap ? cap : 1) {}
};

}  // namespace

PT_EXPORT int64_t pt_queue_create(int capacity) {
  return reinterpret_cast<int64_t>(new BlockingQueue(
      static_cast<size_t>(capacity > 0 ? capacity : 1)));
}

// 0 ok; -1 timeout; -2 closed.
PT_EXPORT int pt_queue_push(int64_t h, const uint8_t* data, int64_t len,
                            int64_t timeout_ms) {
  auto* q = reinterpret_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> g(q->mu);
  auto pred = [&] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(g, pred);
  } else if (!q->not_full.wait_for(g, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -1;
  }
  if (q->closed) return -2;
  if (data == nullptr || len <= 0)
    q->items.emplace_back();
  else
    q->items.emplace_back(reinterpret_cast<const char*>(data),
                          static_cast<size_t>(len));
  q->not_empty.notify_one();
  return 0;
}

// Returns length (>=0) with *out malloc'd; -1 timeout; -2 closed+drained.
PT_EXPORT int64_t pt_queue_pop(int64_t h, int64_t timeout_ms, uint8_t** out) {
  auto* q = reinterpret_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> g(q->mu);
  auto pred = [&] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(g, pred);
  } else if (!q->not_empty.wait_for(g, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed and drained
  std::string item = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  g.unlock();
  *out = static_cast<uint8_t*>(pt::copy_out(item.data(), item.size()));
  return static_cast<int64_t>(item.size());
}

PT_EXPORT int pt_queue_size(int64_t h) {
  auto* q = reinterpret_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> g(q->mu);
  return static_cast<int>(q->items.size());
}

// Close wakes all waiters; pending items remain poppable (drain-then-end).
PT_EXPORT void pt_queue_close(int64_t h) {
  auto* q = reinterpret_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> g(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

PT_EXPORT void pt_queue_destroy(int64_t h) {
  delete reinterpret_cast<BlockingQueue*>(h);
}
