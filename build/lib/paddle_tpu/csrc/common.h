// Shared helpers for the paddle_tpu native runtime layer.
//
// The reference framework's runtime around the compute path is C++
// (allocators, stores, readers, tracers).  On TPU, XLA/PJRT own device
// memory and scheduling, so the native layer here covers the host-side
// runtime the compiler does NOT provide: rendezvous store, bounded
// prefetch queues for the data pipeline, and a low-overhead host tracer.
// Exposed as a C ABI consumed from Python via ctypes (no pybind11 in the
// image).
#pragma once

#include <cstdint>
#include <cstdlib>
#include <cstring>

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

// All buffers returned to the caller are malloc'd; release with
// pt_buffer_free.
PT_EXPORT void pt_buffer_free(void* p);

namespace pt {

inline void* copy_out(const void* src, size_t n) {
  void* p = ::malloc(n ? n : 1);
  if (p && n) ::memcpy(p, src, n);
  return p;
}

}  // namespace pt
