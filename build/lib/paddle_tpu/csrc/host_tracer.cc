// Host tracer: low-overhead span collection + chrome-trace export
// (reference: paddle/fluid/platform/profiler/host_tracer.cc +
// chrometracinglogger.cc).  Device-side tracing on TPU comes from
// jax.profiler/XLA; this collector provides the RecordEvent host spans
// and the summary statistics source, without Python-side allocation in
// the hot path.

#include <sys/syscall.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace {

struct Span {
  std::string name;
  std::string cat;
  int64_t t0_ns;
  int64_t t1_ns;
  int64_t tid;
};

struct Tracer {
  std::mutex mu;
  std::vector<Span> spans;
  bool enabled = false;
};

Tracer g_tracer;

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t tid() { return static_cast<int64_t>(::syscall(SYS_gettid)); }

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          ::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

}  // namespace

PT_EXPORT void pt_tracer_enable(int on) {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.enabled = (on != 0);
}

PT_EXPORT int pt_tracer_enabled() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  return g_tracer.enabled ? 1 : 0;
}

// Begin a span: returns an opaque handle (0 when disabled).
PT_EXPORT int64_t pt_tracer_span_begin(const char* name, const char* cat) {
  {
    std::lock_guard<std::mutex> g(g_tracer.mu);
    if (!g_tracer.enabled) return 0;
  }
  auto* s = new Span{name ? name : "", cat ? cat : "UserDefined", now_ns(), 0,
                     tid()};
  return reinterpret_cast<int64_t>(s);
}

PT_EXPORT void pt_tracer_span_end(int64_t h) {
  if (!h) return;
  auto* s = reinterpret_cast<Span*>(h);
  s->t1_ns = now_ns();
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.spans.emplace_back(std::move(*s));
  delete s;
}

// Record a complete span with caller-supplied timestamps (ns).
PT_EXPORT void pt_tracer_record(const char* name, const char* cat,
                                int64_t t0_ns, int64_t t1_ns) {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  if (!g_tracer.enabled) return;
  g_tracer.spans.push_back(
      Span{name ? name : "", cat ? cat : "UserDefined", t0_ns, t1_ns, tid()});
}

PT_EXPORT int64_t pt_tracer_num_spans() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  return static_cast<int64_t>(g_tracer.spans.size());
}

PT_EXPORT void pt_tracer_clear() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.spans.clear();
}

// Chrome trace-event JSON ("X" complete events, ts/dur in microseconds).
// Returns malloc'd UTF-8 and its length via *out.
PT_EXPORT int64_t pt_tracer_export_chrome(uint8_t** out) {
  std::vector<Span> spans;
  {
    std::lock_guard<std::mutex> g(g_tracer.mu);
    spans = g_tracer.spans;
  }
  std::string j = "{\"traceEvents\":[";
  char buf[256];
  for (size_t i = 0; i < spans.size(); ++i) {
    const Span& s = spans[i];
    if (i) j += ',';
    j += "{\"name\":\"" + json_escape(s.name) + "\",\"cat\":\"" +
         json_escape(s.cat) + "\",\"ph\":\"X\"";
    ::snprintf(buf, sizeof(buf),
               ",\"ts\":%.3f,\"dur\":%.3f,\"pid\":%d,\"tid\":%lld}",
               s.t0_ns / 1e3, (s.t1_ns - s.t0_ns) / 1e3,
               static_cast<int>(::getpid()),
               static_cast<long long>(s.tid));
    j += buf;
  }
  j += "]}";
  *out = static_cast<uint8_t*>(pt::copy_out(j.data(), j.size()));
  return static_cast<int64_t>(j.size());
}

// Packed binary dump for Python-side statistics:
// repeated records of [u32 namelen][name][u32 catlen][cat][i64 t0][i64 t1][i64 tid]
PT_EXPORT int64_t pt_tracer_dump(uint8_t** out) {
  std::vector<Span> spans;
  {
    std::lock_guard<std::mutex> g(g_tracer.mu);
    spans = g_tracer.spans;
  }
  std::string blob;
  for (const Span& s : spans) {
    uint32_t nl = static_cast<uint32_t>(s.name.size());
    uint32_t cl = static_cast<uint32_t>(s.cat.size());
    blob.append(reinterpret_cast<const char*>(&nl), 4);
    blob.append(s.name);
    blob.append(reinterpret_cast<const char*>(&cl), 4);
    blob.append(s.cat);
    blob.append(reinterpret_cast<const char*>(&s.t0_ns), 8);
    blob.append(reinterpret_cast<const char*>(&s.t1_ns), 8);
    blob.append(reinterpret_cast<const char*>(&s.tid), 8);
  }
  *out = static_cast<uint8_t*>(pt::copy_out(blob.data(), blob.size()));
  return static_cast<int64_t>(blob.size());
}
