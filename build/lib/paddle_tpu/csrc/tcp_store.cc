// TCPStore: rank-0-hosted key-value store used for multi-process
// rendezvous and small control-plane exchange (reference:
// paddle/fluid/distributed/store/tcp_store.cc — there it exchanges NCCL
// unique ids; here it bootstraps process groups / barriers around
// jax.distributed, which handles the PJRT coordination itself).
//
// Wire protocol (all little-endian, same-arch cluster assumption):
//   request : u8 op | u32 keylen | key bytes | u64 payloadlen | payload
//   response: u8 status (0 ok, 1 not-found/timeout) | u64 len | bytes
// Ops: SET=1 (payload = value), GET=2 (payload = i64 timeout_ms; blocks
// server-side until key exists), ADD=3 (payload = i64 delta; value kept
// as i64 LE; returns new value), WAIT=4 (payload = i64 timeout_ms),
// DEL=5, NUMKEYS=6.
//
// Server: one acceptor thread + one thread per connection (connections
// are few — one per worker process).  Blocking GET/WAIT sit on a
// condition_variable keyed by the shared map, exactly the reference's
// design.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace {

enum Op : uint8_t { SET = 1, GET = 2, ADD = 3, WAIT = 4, DEL = 5, NUMKEYS = 6 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct StoreServer {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread acceptor;
  std::mutex conn_mu;
  std::vector<std::thread> handlers;
  std::vector<int> conn_fds;

  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> kv;

  ~StoreServer() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    cv.notify_all();
    if (acceptor.joinable()) acceptor.join();
    std::lock_guard<std::mutex> g(conn_mu);
    // Wake handlers parked in recv() on live client connections.
    for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : handlers)
      if (t.joinable()) t.join();
  }

  void handle(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t op;
      uint32_t keylen;
      uint64_t paylen;
      if (!read_full(fd, &op, 1) || !read_full(fd, &keylen, 4)) break;
      std::string key(keylen, '\0');
      if (keylen && !read_full(fd, &key[0], keylen)) break;
      if (!read_full(fd, &paylen, 8)) break;
      std::string payload(paylen, '\0');
      if (paylen && !read_full(fd, &payload[0], paylen)) break;

      uint8_t status = 0;
      std::string out;
      switch (op) {
        case SET: {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = payload;
          cv.notify_all();
          break;
        }
        case GET:
        case WAIT: {
          if (payload.size() < sizeof(int64_t)) {
            status = 1;
            break;
          }
          int64_t timeout_ms;
          ::memcpy(&timeout_ms, payload.data(), sizeof(timeout_ms));
          std::unique_lock<std::mutex> g(mu);
          auto pred = [&] { return stop.load() || kv.count(key) > 0; };
          bool ok;
          if (timeout_ms < 0) {
            cv.wait(g, pred);
            ok = kv.count(key) > 0;
          } else {
            ok = cv.wait_for(g, std::chrono::milliseconds(timeout_ms), pred) &&
                 kv.count(key) > 0;
          }
          if (!ok) {
            status = 1;
          } else if (op == GET) {
            out = kv[key];
          }
          break;
        }
        case ADD: {
          if (payload.size() < sizeof(int64_t)) {
            status = 1;
            break;
          }
          int64_t delta;
          ::memcpy(&delta, payload.data(), sizeof(delta));
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == sizeof(int64_t))
            ::memcpy(&cur, it->second.data(), sizeof(cur));
          cur += delta;
          kv[key].assign(reinterpret_cast<const char*>(&cur), sizeof(cur));
          out.assign(reinterpret_cast<const char*>(&cur), sizeof(cur));
          cv.notify_all();
          break;
        }
        case DEL: {
          std::lock_guard<std::mutex> g(mu);
          status = kv.erase(key) ? 0 : 1;
          break;
        }
        case NUMKEYS: {
          std::lock_guard<std::mutex> g(mu);
          int64_t n = static_cast<int64_t>(kv.size());
          out.assign(reinterpret_cast<const char*>(&n), sizeof(n));
          break;
        }
        default:
          status = 1;
      }
      uint64_t outlen = out.size();
      if (!write_full(fd, &status, 1) || !write_full(fd, &outlen, 8) ||
          (outlen && !write_full(fd, out.data(), outlen)))
        break;
    }
    ::close(fd);
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      std::lock_guard<std::mutex> g(conn_mu);
      conn_fds.push_back(fd);
      handlers.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct StoreClient {
  int fd = -1;
  std::mutex mu;  // serialize request/response pairs

  ~StoreClient() {
    if (fd >= 0) ::close(fd);
  }

  // status 0 ok; 1 miss/timeout; -1 transport error
  int request(uint8_t op, const char* key, const void* payload,
              uint64_t paylen, std::string* out) {
    std::lock_guard<std::mutex> g(mu);
    uint32_t keylen = static_cast<uint32_t>(::strlen(key));
    if (!write_full(fd, &op, 1) || !write_full(fd, &keylen, 4) ||
        !write_full(fd, key, keylen) || !write_full(fd, &paylen, 8) ||
        (paylen && !write_full(fd, payload, paylen)))
      return -1;
    uint8_t status;
    uint64_t outlen;
    if (!read_full(fd, &status, 1) || !read_full(fd, &outlen, 8)) return -1;
    out->resize(outlen);
    if (outlen && !read_full(fd, &(*out)[0], outlen)) return -1;
    return status;
  }
};

}  // namespace

PT_EXPORT void pt_buffer_free(void* p) { ::free(p); }

PT_EXPORT int64_t pt_store_server_start(int port) {
  auto* s = new StoreServer();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return 0;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(s->listen_fd, 128) < 0) {
    delete s;
    return 0;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->acceptor = std::thread([s] { s->accept_loop(); });
  return reinterpret_cast<int64_t>(s);
}

PT_EXPORT int pt_store_server_port(int64_t h) {
  return reinterpret_cast<StoreServer*>(h)->port;
}

PT_EXPORT void pt_store_server_stop(int64_t h) {
  auto* s = reinterpret_cast<StoreServer*>(h);
  s->shutdown();
  delete s;
}

PT_EXPORT int64_t pt_store_client_connect(const char* host, int port,
                                          int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portstr[16];
    ::snprintf(portstr, sizeof(portstr), "%d", port);
    if (::getaddrinfo(host, portstr, &hints, &res) == 0 && res) {
      int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        ::freeaddrinfo(res);
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto* c = new StoreClient();
        c->fd = fd;
        return reinterpret_cast<int64_t>(c);
      }
      if (fd >= 0) ::close(fd);
      ::freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() >= deadline) return 0;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

PT_EXPORT void pt_store_client_close(int64_t h) {
  delete reinterpret_cast<StoreClient*>(h);
}

PT_EXPORT int pt_store_set(int64_t h, const char* key, const uint8_t* data,
                           int64_t len) {
  std::string out;
  return reinterpret_cast<StoreClient*>(h)->request(SET, key, data,
                                                    static_cast<uint64_t>(len),
                                                    &out);
}

// Returns value length (>=0) and sets *out (malloc'd); -1 on
// miss/timeout, -2 on transport error.
PT_EXPORT int64_t pt_store_get(int64_t h, const char* key, int64_t timeout_ms,
                               uint8_t** out) {
  std::string v;
  int st = reinterpret_cast<StoreClient*>(h)->request(
      GET, key, &timeout_ms, sizeof(timeout_ms), &v);
  if (st != 0) return st == 1 ? -1 : -2;
  *out = static_cast<uint8_t*>(pt::copy_out(v.data(), v.size()));
  return static_cast<int64_t>(v.size());
}

// Returns the post-add counter value; INT64_MIN on error.
PT_EXPORT int64_t pt_store_add(int64_t h, const char* key, int64_t delta) {
  std::string v;
  int st = reinterpret_cast<StoreClient*>(h)->request(ADD, key, &delta,
                                                      sizeof(delta), &v);
  if (st != 0 || v.size() != sizeof(int64_t)) return INT64_MIN;
  int64_t r;
  ::memcpy(&r, v.data(), sizeof(r));
  return r;
}

PT_EXPORT int pt_store_wait(int64_t h, const char* key, int64_t timeout_ms) {
  std::string v;
  return reinterpret_cast<StoreClient*>(h)->request(WAIT, key, &timeout_ms,
                                                    sizeof(timeout_ms), &v);
}

PT_EXPORT int pt_store_delete(int64_t h, const char* key) {
  std::string v;
  return reinterpret_cast<StoreClient*>(h)->request(DEL, key, nullptr, 0, &v);
}

PT_EXPORT int64_t pt_store_num_keys(int64_t h) {
  std::string v;
  int st = reinterpret_cast<StoreClient*>(h)->request(NUMKEYS, "", nullptr, 0,
                                                      &v);
  if (st != 0 || v.size() != sizeof(int64_t)) return -1;
  int64_t r;
  ::memcpy(&r, v.data(), sizeof(r));
  return r;
}
