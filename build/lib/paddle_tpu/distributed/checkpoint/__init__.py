"""Sharding-aware distributed checkpointing with reshard-on-load
(reference: the per-wrapper shard-aware state_dicts —
GroupShardedStage3.state_dict, HybridParallelOptimizer per-rank shards,
auto_parallel dist_saver — unified here per SURVEY §5.4 into ONE subsystem
like the auto-parallel dist_saver, not a per-wrapper zoo).

TPU-native design: every jax.Array already knows its sharding; ``save``
writes each process's addressable shards (one .npy per shard + a JSON
index of global shape/dtype/slices), so N hosts write N disjoint file
sets with no gather.  ``load`` assembles each target device's slab by
reading only the byte ranges that overlap it (numpy mmap) and builds the
array with ``jax.make_array_from_single_device_arrays`` under the NEW
sharding — loading into a different mesh/parallel degree (elastic resume,
TP→FSDP regrouping) is the same code path as same-mesh load.
``async_save=True`` snapshots shards to host synchronously (cheap D2H)
and writes to disk on a background thread, returning a waitable handle —
the orbax/tensorstore pattern.
"""
import json
import os
import re
import threading
import time
import uuid

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle"]

_META = "checkpoint.metadata.json"


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _safe(key):
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", key)


def _as_array(v):
    if isinstance(v, Tensor):
        return v._value
    return v


class AsyncSaveHandle:
    """Returned by save_state_dict(async_save=True).  The checkpoint is not
    loadable until the write completes (metadata is committed last, via
    atomic rename) — call ``wait()`` before relying on it."""

    def __init__(self, target):
        self.exception = None

        def runner():
            try:
                target()
            except Exception as e:      # surfaced at wait()
                self.exception = e
        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def wait(self):
        self._thread.join()
        if self.exception is not None:
            raise self.exception
        return True

    def done(self):
        return not self._thread.is_alive()


def _default_generation():
    """A save-generation id every process of one save agrees on.

    Saving into a directory that already holds rank metadata from a prior
    save with a DIFFERENT world size leaves stale rank files behind; the
    loader must not merge shard records across save generations (elastic
    resume across mesh changes would silently mix tensor data).  Single
    process: a fresh uuid.  Multi process: rank 0's uuid broadcast to all,
    so every rank stamps the same id.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        seed = np.frombuffer(uuid.uuid4().bytes[:8], dtype=np.int64)
        seed = multihost_utils.broadcast_one_to_all(seed)
        return f"{int(seed[0]) & (2**63 - 1):016x}"
    return uuid.uuid4().hex


def save_state_dict(state_dict, path, process_index=None, async_save=False,
                    generation=None):
    """Write this process's addressable shards of every array leaf.

    Layout::

        path/checkpoint.metadata.rank<P>.json  (per process, committed LAST
                                                via atomic rename — an
                                                aborted save has no
                                                metadata and fails loudly)
        path/<key>/shard_<flat_start_idx>.npy

    Keys are the flattened dotted names exactly as produced by
    ``Layer.state_dict()``; ``load_state_dict`` returns the same flat keys.
    Every process records its OWN shards in its own metadata file; the
    loader merges all rank files, so multi-host saves need no gather.

    Each save is stamped with a ``generation`` id shared by all of its
    ranks (see :func:`_default_generation`); the loader merges only the
    newest generation, so re-saving into a directory that still holds rank
    files from a larger world size cannot mix checkpoints.  Pass an
    explicit ``generation`` (e.g. the global step as a string) to override
    — all ranks must pass the same value.
    """
    if generation is None:
        if process_index is None:
            # auto mode: we know how to mint an id all ranks share
            generation = _default_generation()
        # else: explicit process_index (rank-by-rank simulation / tests)
        # with no shared id available — leave the save unstamped so the
        # per-rank files merge as one legacy generation, exactly the
        # pre-generation behavior.  Pass generation= (e.g. the step) to
        # opt into stale-file protection on this path.
    process_index = (jax.process_index() if process_index is None
                     else process_index)
    flat = {k: _as_array(v) for k, v in _flatten(state_dict).items()}
    os.makedirs(path, exist_ok=True)

    meta = {"arrays": {}, "format": 3, "saved_at_ns": time.time_ns()}
    if generation is not None:
        meta["generation"] = str(generation)
    jobs = []   # (filepath, host numpy array)
    for key, arr in flat.items():
        if not isinstance(arr, jax.Array):
            arr = jnp.asarray(arr)
        entry = {"global_shape": list(arr.shape), "dtype": str(arr.dtype),
                 "shards": []}
        is_bf16 = arr.dtype == jnp.bfloat16
        seen_starts = set()
        for shard in arr.addressable_shards:
            # replicated copies: exactly ONE owner writes (replica 0),
            # keeping multi-host file sets disjoint
            if shard.replica_id != 0:
                continue
            idx = shard.index   # tuple of slices into the global array
            starts = tuple((s.start or 0) for s in idx)
            if starts in seen_starts:
                continue
            seen_starts.add(starts)
            sizes = [
                (s.stop if s.stop is not None else arr.shape[d])
                - (s.start or 0) for d, s in enumerate(idx)]
            fname = (f"{_safe(key)}/shard_" +
                     "_".join(str(s) for s in starts) + ".npy")
            entry["shards"].append({"starts": list(starts), "sizes": sizes,
                                    "file": fname})
            # D2H snapshot now; disk write possibly async.  bf16 has no
            # stable npy representation — store the uint16 bit pattern.
            data = np.asarray(shard.data)
            if is_bf16:
                data = data.view(np.uint16)
            jobs.append((os.path.join(path, fname), data))
        meta["arrays"][key] = entry

    meta_path = os.path.join(path, f"checkpoint.metadata.rank"
                                   f"{process_index}.json")

    def write_all():
        for fpath, data in jobs:
            os.makedirs(os.path.dirname(fpath), exist_ok=True)
            tmp_f = f"{fpath}.tmp.{process_index}"
            with open(tmp_f, "wb") as f:   # file-object save: no .npy suffix
                np.save(f, data)
            os.replace(tmp_f, fpath)
        # commit: metadata appears only after every shard is on disk
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)

    if async_save:
        return AsyncSaveHandle(write_all)
    write_all()
    return None


def _read_region(path, shard_rec, region, is_bf16=False):
    """Read the intersection of one saved shard with a target region.

    region: list of (start, stop) in global coords.  Returns (slab_slices,
    data) where slab_slices places the data inside the target slab."""
    starts = shard_rec["starts"]
    sizes = shard_rec["sizes"]
    inter_src, inter_dst = [], []
    for d, ((rs, re_), s0, sz) in enumerate(zip(region, starts, sizes)):
        lo = max(rs, s0)
        hi = min(re_, s0 + sz)
        if lo >= hi:
            return None, None
        inter_src.append(slice(lo - s0, hi - s0))
        inter_dst.append(slice(lo - rs, hi - rs))
    data = np.load(path, mmap_mode="r")[tuple(inter_src)]
    data = np.ascontiguousarray(data)
    if is_bf16:   # stored as uint16 bit pattern (see save_state_dict)
        data = data.view(jnp.bfloat16)
    return tuple(inter_dst), data


def _assemble_region(ckpt_path, entry, region, dtype):
    is_bf16 = entry["dtype"] == "bfloat16"
    slab = np.zeros([hi - lo for lo, hi in region], dtype)
    for shard_rec in entry["shards"]:
        dst, data = _read_region(
            os.path.join(ckpt_path, shard_rec["file"]), shard_rec, region,
            is_bf16)
        if dst is not None:
            slab[dst] = np.asarray(data).reshape(slab[dst].shape)
    return slab


def _merged_meta(path):
    """Union of the NEWEST save generation's rank metadata.

    Multi-host saves write one rank file each, all stamped with a shared
    generation id.  A directory can legitimately hold stale rank files
    from an earlier save with a larger world size (elastic resume across
    mesh changes); merging across generations would silently mix tensor
    data, so only files whose generation matches the most recently written
    one are merged.  Pre-generation (format<=2) files have no stamp and
    are treated as one legacy generation.
    """
    import glob
    files = sorted(glob.glob(os.path.join(
        path, "checkpoint.metadata.rank*.json")))
    legacy = os.path.join(path, _META)
    if not files and os.path.exists(legacy):
        files = [legacy]
    if not files:
        raise FileNotFoundError(
            f"no checkpoint metadata under {path} — incomplete/aborted "
            "save, or wrong directory")
    metas = []
    for fp in files:
        with open(fp) as f:
            meta = json.load(f)
        m = re.search(r"rank(\d+)", os.path.basename(fp))
        rank = int(m.group(1)) if m else 0
        metas.append((meta.get("generation"), rank, meta))
    # The current generation is whatever the LOWEST-rank file carries:
    # every save includes process 0, so a re-save always rewrites the
    # lowest rank file, while wallclock stamps are cross-host clocks and
    # can make a stale higher-rank file look newest.
    newest_gen = min(metas, key=lambda m: m[1])[0]
    selected = [m for gen, _, m in metas if gen == newest_gen]
    merged = {"arrays": {}}
    for meta in selected:
        for key, entry in meta["arrays"].items():
            cur = merged["arrays"].get(key)
            if cur is None:
                merged["arrays"][key] = {
                    "global_shape": entry["global_shape"],
                    "dtype": entry["dtype"],
                    "shards": list(entry["shards"])}
            else:
                seen = {tuple(s["starts"]) for s in cur["shards"]}
                cur["shards"].extend(
                    s for s in entry["shards"]
                    if tuple(s["starts"]) not in seen)
    return merged


def load_state_dict(path, template=None, shardings=None, mesh=None):
    """Load a checkpoint, resharding every array onto its target sharding.

    Returns a FLAT dict keyed exactly as saved (dotted Layer.state_dict
    names round-trip into ``set_state_dict`` unchanged).  Target selection,
    in priority order: ``shardings`` (flat-key → jax.sharding.Sharding),
    the sharding of the same-keyed array in ``template`` (a state_dict of
    arrays/Tensors laid out how the caller wants them), or
    fully-replicated on ``mesh``/default device.  Loading into a different
    mesh shape than the save ran on is the normal case, not an error.
    """
    meta = _merged_meta(path)
    tmpl_flat = ({k: _as_array(v) for k, v in _flatten(template).items()}
                 if template is not None else {})
    out = {}
    for key, entry in meta["arrays"].items():
        shape = tuple(entry["global_shape"])
        dtype = np.dtype(entry["dtype"]) if entry["dtype"] != "bfloat16" \
            else jnp.bfloat16
        target = None
        if shardings is not None and key in shardings:
            target = shardings[key]
        elif key in tmpl_flat and isinstance(tmpl_flat[key], jax.Array):
            target = tmpl_flat[key].sharding
        if target is None:
            full = _assemble_region(path, entry,
                                    [(0, s) for s in shape], dtype)
            arr = jnp.asarray(full)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                arr = jax.device_put(
                    arr, NamedSharding(mesh, PartitionSpec()))
            out[key] = arr
            continue
        # build per-device slabs for the target sharding; devices sharing a
        # region (replication) reuse one host slab
        device_map = target.addressable_devices_indices_map(shape)
        slab_cache = {}
        slabs = []
        for dev, idx in device_map.items():
            region = []
            for d, s in enumerate(idx):
                start = s.start or 0
                stop = s.stop if s.stop is not None else shape[d]
                region.append((start, stop))
            rkey = tuple(region)
            if rkey not in slab_cache:
                slab_cache[rkey] = _assemble_region(path, entry, region,
                                                    dtype)
            slabs.append(jax.device_put(slab_cache[rkey], dev))
        out[key] = jax.make_array_from_single_device_arrays(
            shape, target, slabs)
    return out
