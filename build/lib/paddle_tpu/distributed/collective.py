"""Communication API (reference: python/paddle/distributed/communication/
over ProcessGroupNCCL — paddle/fluid/distributed/collective/).

TPU-native: the transport is XLA collectives over ICI/DCN.  Inside a
``shard_map``/``pjit`` trace these functions lower to ``lax.psum`` /
``all_gather`` / ``all_to_all`` / ``ppermute`` on the named mesh axis; in
eager single-process mode they are the world-size-1 identity (matching the
reference's behavior when nranks==1).  Async ``Task`` semantics come free
from XLA's async collectives, so ``wait`` is a barrier on the value.

Groups name mesh axes rather than holding NCCL communicators: ``new_group``
returns a ``Group`` carrying the axis name(s) the collective should ride.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor
from ..framework.autograd import call_op
from .env import get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group ≙ one or more mesh axis names."""

    def __init__(self, axis_name=None, ranks=None, group_id=0):
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.id = group_id
        self.nranks = len(self.ranks) if self.ranks else None

    @property
    def world_size(self):
        if self.nranks:
            return self.nranks
        return get_world_size()

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        if self.ranks:
            return self.ranks.index(rank) if rank in self.ranks else -1
        return rank

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


_GROUPS = {}
_GROUP_COUNTER = [0]
_WORLD = Group(axis_name=None, group_id=0)


def _in_named_trace(axis):
    """True if `axis` is a bound mapped axis (inside shard_map/pmap)."""
    if axis is None:
        return False
    try:
        lax.axis_index(axis)  # raises NameError outside a binding context
        return True
    except (NameError, Exception):
        return False


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    _GROUP_COUNTER[0] += 1
    g = Group(axis_name=axis_name, ranks=ranks,
              group_id=_GROUP_COUNTER[0])
    _GROUPS[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _WORLD
    return _GROUPS.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _GROUPS.clear()
    else:
        _GROUPS.pop(group.id, None)


def _axis_of(group):
    if group is None:
        return None
    return group.axis_name


def _apply(x, fn):
    """Run fn over a Tensor through the tape (collectives are
    autograd-aware: psum's transpose is psum etc., handled by jax)."""
    if isinstance(x, Tensor):
        return call_op(fn, x)
    return Tensor(fn(jnp.asarray(x)))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        red = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
               ReduceOp.MIN: lax.pmin,
               ReduceOp.AVG: lambda v, a: lax.pmean(v, a)}[op]
        out = _apply(tensor, lambda v: red(v, axis))
    else:
        out = tensor  # world of 1 (or replicated eager value): identity
    if isinstance(tensor, Tensor) and isinstance(out, Tensor) \
            and out is not tensor:
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # On an SPMD mesh every shard computes the reduction (XLA has no
    # rooted reduce); semantically equivalent for the framework's uses.
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(tensor, lambda v: lax.all_gather(v, axis))
        n = out.shape[0]
        parts = [out[i] for i in range(n)]
    else:
        parts = [tensor]
    tensor_list.clear()
    tensor_list.extend(parts)
    return _Task(tensor_list)


def all_gather_object(object_list, obj, group=None):
    object_list.clear()
    object_list.append(obj)
    return _Task(object_list)


def all_gather_into_tensor(out_tensor, tensor, group=None, sync_op=True,
                           concat_axis=0):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(tensor, lambda v: lax.all_gather(
            v, axis, tiled=True, axis=concat_axis))
    else:
        out = tensor
    out_tensor._value = out._value
    out_tensor._node = out._node
    out_tensor._out_idx = out._out_idx
    out_tensor.stop_gradient = out.stop_gradient
    return _Task(out_tensor)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    axis = _axis_of(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat
        src = concat(list(src), axis=0)
    if axis is not None and _in_named_trace(axis):
        out = _apply(src, lambda v: lax.psum_scatter(
            v, axis, scatter_dimension=0, tiled=True))
    else:
        out = src
    tensor._value = out._value
    tensor._node = out._node
    tensor._out_idx = out._out_idx
    tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis_of(group)
    from ..tensor.manipulation import stack
    x = stack(list(in_tensor_list), axis=0)
    if axis is not None and _in_named_trace(axis):
        out = _apply(x, lambda v: lax.all_to_all(
            v, axis, split_axis=0, concat_axis=0, tiled=False))
        parts = [out[i] for i in range(out.shape[0])]
    else:
        parts = list(in_tensor_list)
    out_tensor_list.clear()
    out_tensor_list.extend(parts)
    return _Task(out_tensor_list)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(in_tensor, lambda v: lax.all_to_all(
            v, axis, split_axis=0, concat_axis=0, tiled=True))
    else:
        out = in_tensor
    out_tensor._value = out._value
    out_tensor._node = out._node
    out_tensor._out_idx = out._out_idx
    out_tensor.stop_gradient = out.stop_gradient
    return _Task(out_tensor)


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        # select src rank's shard everywhere via all_gather + index
        out = _apply(tensor, lambda v: lax.all_gather(v, axis)[src])
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis) and tensor_list:
        from ..tensor.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)
        idx = lax.axis_index(axis)
        out = _apply(stacked, lambda v: v[idx])
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    elif tensor_list:
        tensor._value = tensor_list[src]._value
    return _Task(tensor)


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv are not exposed eagerly on TPU; use "
        "paddle_tpu.distributed.p2p.ppermute inside a shard_map (the "
        "pipeline runtime does this), or batch_isend_irecv")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv are not exposed eagerly on TPU; use "
        "paddle_tpu.distributed.p2p.ppermute inside a shard_map")


def ppermute(tensor, perm, group=None):
    """P2P as collective-permute (TPU's native send/recv). perm: list of
    (src, dst) pairs; must run inside shard_map on the group's axis."""
    axis = _axis_of(group)
    return _apply(tensor, lambda v: lax.ppermute(v, axis, perm))


def barrier(group=None):
    # XLA programs are bulk-synchronous; an explicit barrier is only
    # meaningful across processes.
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        try:
            tensor._value.block_until_ready()
        except Exception:
            pass


class _Task:
    def __init__(self, result):
        self._result = result

    def wait(self):
        if isinstance(self._result, Tensor):
            wait(self._result)
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        self.wait()


class stream:
    """paddle.distributed.stream.* compat namespace."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
    reduce = staticmethod(reduce)
