"""Hybrid topology (reference:
python/paddle/distributed/fleet/base/topology.py — CommunicateTopology +
HybridCommunicateGroup building per-axis comm groups over NCCL).

TPU-native: the topology IS a jax.sharding.Mesh with named axes in the
canonical order [dp, pp, sharding, sep, mp] (reference order kept so rank
mapping matches).  "Comm groups" become axis names; collectives ride the
axis inside shard_map/pjit, with XLA mapping them onto the ICI torus —
axis placement follows jax.make_mesh's device assignment, which puts the
fastest-varying (innermost) axis on the tightest ICI loop, so mp gets the
best bandwidth exactly like the reference's ring-order heuristics.
"""
import numpy as np
import jax
from jax.sharding import Mesh

from ...collective import new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_AXIS_ORDER = ["data", "pipe", "sharding", "sep", "model"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = list(hybrid_group_names or _AXIS_ORDER)
        self._dims = list(dims or [1] * len(self._names))
        assert len(self._names) == len(self._dims)
        self._world = int(np.prod(self._dims))
        self._rank_array = np.arange(self._world).reshape(self._dims)

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        idx = tuple(kwargs[n] for n in self._names)
        return int(self._rank_array[idx])

    def get_coord(self, rank):
        idx = np.argwhere(self._rank_array == rank)[0]
        from collections import namedtuple
        Coord = namedtuple("Coord", self._names)
        return Coord(*[int(i) for i in idx])

    def get_axis_list(self, axis_name, index):
        ax = self._names.index(axis_name)
        taken = np.take(self._rank_array, index, axis=ax)
        return sorted(int(i) for i in taken.reshape(-1))

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank-lists."""
        ax = self._names.index(axis_name)
        moved = np.moveaxis(self._rank_array, ax, -1)
        return [list(map(int, row)) for row in
                moved.reshape(-1, self._dims[ax])]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)._asdict()
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Accessors for per-axis groups + the jax Mesh that backs compiled
    collective code."""

    def __init__(self, topology):
        self._topo = topology
        from ...env import get_rank
        self.global_rank = get_rank()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("model")
        self._groups = {}
        for name in topology.get_hybrid_group_names():
            self._groups[name] = new_group(
                ranks=topology.get_axis_list(
                    name, 0) if topology.get_dim(name) > 1 else [0],
                axis_name=name)
        self._jax_mesh = None

    # -- mesh ---------------------------------------------------------------
    @property
    def jax_mesh(self):
        """Lazily build the device mesh matching the topology (requires
        world_size == visible device count for single-process SPMD)."""
        if self._jax_mesh is None:
            devs = np.asarray(jax.devices())
            need = self._topo.world_size()
            if len(devs) < need:
                raise RuntimeError(
                    f"topology needs {need} devices, have {len(devs)}")
            names = tuple(self._topo.get_hybrid_group_names())
            dims = [self._topo.get_dim(n) for n in names]
            self._jax_mesh = Mesh(devs[:need].reshape(dims), names)
        return self._jax_mesh

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # -- degree accessors ----------------------------------------------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    # -- rank accessors ------------------------------------------------------
    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def get_data_parallel_rank(self):
        return self._coord().data

    def get_model_parallel_rank(self):
        return self._coord().model

    def get_stage_id(self):
        return self._coord().pipe

    def get_sharding_parallel_rank(self):
        return self._coord().sharding

    def get_sep_parallel_rank(self):
        return getattr(self._coord(), "sep", 0)

    # -- group accessors -----------------------------------------------------
    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def get_check_parallel_group(self, *a):
        return self._groups["model"]

    def get_data_parallel_group_src_rank(self):
        return self._topo.get_axis_list("data", 0)[0]

    def get_model_parallel_group_src_rank(self):
        return self._topo.get_axis_list("model", 0)[0]

    # -- pipeline helpers ----------------------------------------------------
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)
