"""Elastic training (reference:
python/paddle/distributed/fleet/elastic/manager.py — ETCD-based node
membership with lease+heartbeat, scale-in/out watch, relaunch with new
ranks within an ``--np min:max`` range).

TPU-native: the membership registry is the framework's own TCPStore (the
same rendezvous store used for comm bootstrap) instead of an external ETCD
cluster; semantics are identical — register with a heartbeat lease, watch
the member set, and report RESTART/HOLD/NORMAL to the launcher, which
tears down workers and relaunches with recomputed
``PADDLE_TRAINER_ENDPOINTS``.  Multi-host TPU jobs pair this with fast
sharded-checkpoint resume (SURVEY §5.3).
"""
import json
import os
import threading
import time

from ...store import TCPStore

__all__ = ["ElasticStatus", "ElasticLevel", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"          # below min nodes: wait
    RESTART = "restart"    # membership changed: relaunch with new ranks
    NORMAL = "normal"
    EXIT = "exit"


class ElasticLevel:
    NONE = 0
    FAULT_TOLERANCE = 1    # fixed np, survive restarts
    ELASTIC = 2            # np range, scale in/out


class ElasticManager:
    """Store-backed membership manager.

    Parameters mirror the reference manager: ``np`` is "N" or "min:max",
    ``host``/``curr_port`` identify this node, ``scale``/``force`` knobs
    kept for CLI compat.
    """

    _PREFIX = "elastic"

    def __init__(self, np="1", host=None, store=None, master=None,
                 heartbeat_interval=2.0, elastic_timeout=30.0,
                 job_id="default"):
        np = str(np)
        if ":" in np:
            lo, hi = np.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(np)
        self.elastic_level = (ElasticLevel.ELASTIC
                              if self.max_np > self.min_np
                              else ElasticLevel.FAULT_TOLERANCE)
        self.host = host or os.environ.get("POD_IP", "127.0.0.1")
        self.job_id = job_id
        self.heartbeat_interval = heartbeat_interval
        self.elastic_timeout = elastic_timeout
        if store is not None:
            self._store = store
        else:
            master = master or os.environ.get("PADDLE_MASTER",
                                              "127.0.0.1:6768")
            h, p = master.rsplit(":", 1)
            self._store = TCPStore(h, int(p), is_master=False)
        self._node_id = None
        self._hb_thread = None
        self._stopped = threading.Event()
        self._last_members = None
        # ids with no readable record get backoff deadlines instead of a
        # permanent blacklist: transient store slowness must not evict a
        # live peer (they are re-probed after the backoff lapses)
        self._dead_until = {}
        self._miss_counts = {}
        self.enabled = self.elastic_level != ElasticLevel.NONE

    # -- keys ---------------------------------------------------------------
    def _k(self, *parts):
        return "/".join((self._PREFIX, self.job_id) + parts)

    # -- lifecycle ----------------------------------------------------------
    def start(self, endpoint=None):
        """Register this node and start the heartbeat lease."""
        self._node_id = self._store.add(self._k("seq"), 1) - 1
        self._endpoint = endpoint or f"{self.host}:0"
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self._node_id

    def _beat(self):
        rec = {"endpoint": self._endpoint, "ts": time.time(), "alive": True}
        self._store.set(self._k("node", str(self._node_id)),
                        json.dumps(rec).encode())

    def _hb_loop(self):
        while not self._stopped.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                return

    def stop(self):
        self._stopped.set()
        if self._node_id is not None:
            try:
                rec = {"endpoint": self._endpoint, "ts": 0, "alive": False}
                self._store.set(self._k("node", str(self._node_id)),
                                json.dumps(rec).encode())
            except Exception:
                pass

    def exit(self, completed=True):
        self.stop()
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR

    # -- membership ---------------------------------------------------------
    def _members(self):
        """Fresh member records {node_id: endpoint} (heartbeat within the
        lease window), capped at max_np (lowest ids win, matching the
        reference's membership cap).  This node is always included from
        local knowledge, so a transient store hiccup can never hand our
        rank to someone else.  Ids that repeatedly have no record (died
        between registration and first heartbeat) are remembered as dead
        and skipped, keeping watch() latency flat."""
        try:
            seq = self._store.add(self._k("seq"), 0)
        except Exception:
            seq = 0
        now = time.time()
        lease = max(self.heartbeat_interval * 3, 6.0)
        members = {}
        for nid in range(seq):
            if self._dead_until.get(nid, 0) > now:
                continue
            try:
                raw = self._store.get(self._k("node", str(nid)),
                                      timeout=1.0)
            except Exception:
                self._miss_counts[nid] = self._miss_counts.get(nid, 0) + 1
                if self._miss_counts[nid] >= 3:
                    self._dead_until[nid] = now + 10 * lease
                continue
            self._miss_counts.pop(nid, None)
            self._dead_until.pop(nid, None)
            try:
                rec = json.loads(raw.decode())
            except Exception:
                continue
            if rec.get("alive") and now - rec["ts"] <= lease:
                members[nid] = rec["endpoint"]
        if self._node_id is not None and not self._stopped.is_set():
            members.setdefault(self._node_id, getattr(self, "_endpoint",
                                                      f"{self.host}:0"))
        if len(members) > self.max_np:
            keep = sorted(members)[:self.max_np]
            members = {k: members[k] for k in keep}
        return members

    def endpoints(self):
        """Ordered endpoint list of the current membership (rank order =
        node-id order, the reference's sorted-hosts rule)."""
        m = self._members()
        return [m[k] for k in sorted(m)]

    def watch(self):
        """One membership poll → status for the launcher loop."""
        members = self._members()
        n = len(members)
        if self._last_members is None:
            self._last_members = members
        if n < self.min_np:
            return ElasticStatus.HOLD
        if members != self._last_members:
            self._last_members = members
            return ElasticStatus.RESTART
        return ElasticStatus.NORMAL

    def wait_for_np(self, timeout=None):
        """Block until member count is within [min_np, max_np]."""
        timeout = timeout if timeout is not None else self.elastic_timeout
        t0 = time.time()
        while time.time() - t0 < timeout:
            n = len(self._members())
            if self.min_np <= n <= self.max_np:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False
