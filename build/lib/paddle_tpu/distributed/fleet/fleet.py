"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py).

``fleet.init(strategy)`` builds the hybrid topology;
``distributed_model``/``distributed_optimizer`` wrap by parallel mode —
here they compile the DistributedStrategy into mesh-axis sharding rules
(M2/M4 wire DP/sharding/TP/PP wrappers in meta_parallel/).
"""
import numpy as np
import jax

from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from ..env import init_parallel_env, get_rank, get_world_size

_FLEET = {"strategy": None, "hcg": None, "initialized": False}


class Fleet:
    def __init__(self):
        pass

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        if strategy is None:
            strategy = DistributedStrategy()
        _FLEET["strategy"] = strategy
        init_parallel_env()
        h = strategy.hybrid_configs
        n_dev = jax.device_count()
        degrees = {"data": h.get("dp_degree", 1),
                   "pipe": h.get("pp_degree", 1),
                   "sharding": h.get("sharding_degree", 1),
                   "sep": h.get("sep_degree", 1),
                   "model": h.get("mp_degree", 1)}
        specified = int(np.prod(list(degrees.values())))
        if degrees["data"] == 1 and specified < n_dev and \
                n_dev % max(specified, 1) == 0:
            # reference behavior: dp fills the remainder
            degrees["data"] = n_dev // specified
        topo = CommunicateTopology(list(degrees.keys()),
                                   list(degrees.values()))
        _FLEET["hcg"] = HybridCommunicateGroup(topo)
        _FLEET["initialized"] = True
        return self

    @property
    def is_initialized(self):
        return _FLEET["initialized"]

    def distributed_model(self, model):
        from .meta_parallel import wrap_distributed_model
        return wrap_distributed_model(model, _FLEET["strategy"],
                                      _FLEET["hcg"])

    def distributed_optimizer(self, optimizer, strategy=None):
        from .meta_parallel import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer,
                                       _FLEET["hcg"],
                                       strategy or _FLEET["strategy"])

    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def is_first_worker(self):
        return get_rank() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def get_hybrid_communicate_group(self):
        return _FLEET["hcg"]

    @property
    def strategy(self):
        return _FLEET["strategy"]

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        pass

    def stop_worker(self):
        pass


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
