"""Fleet meta-optimizers (reference:
python/paddle/distributed/fleet/meta_optimizers/ — strategy-driven
optimizer rewrites applied by fleet.distributed_optimizer: LarsOptimizer,
DGCOptimizer, LocalSGDOptimizer, GradientMergeOptimizer, ...).

TPU-native: AMP/recompute/sharding/TP/PP strategies are placements (the
engine compiles them into the step); what remains as genuine *optimizer*
rewrites is this module: Lars/DGC swap a Momentum inner optimizer for
the adaptive/compressed variant, GradientMerge accumulates k micro-grads
before one apply, LocalSGD syncs params periodically instead of grads
every step.
"""
import jax.numpy as jnp

from ....optimizer.optimizer import (Momentum, LarsMomentum, DGCMomentum)

__all__ = ["apply_meta_optimizers", "GradientMergeHelper",
           "LocalSGDOptimizer"]


def apply_meta_optimizers(optimizer, strategy):
    """Strategy-driven inner-optimizer replacement (reference:
    fleet._final_strategy meta-optimizer pass).  Returns the (possibly
    replaced/wrapped) optimizer."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "lars", False) and type(optimizer) is Momentum:
        cfg = getattr(strategy, "lars_configs", None) or {}
        optimizer = LarsMomentum(
            learning_rate=optimizer._learning_rate,
            momentum=optimizer._momentum,
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip,
            exclude_from_weight_decay=cfg.get(
                "exclude_from_weight_decay", []),
            epsilon=cfg.get("epsilon", 1e-9))
    elif getattr(strategy, "dgc", False) and type(optimizer) is Momentum:
        cfg = getattr(strategy, "dgc_configs", None) or {}
        optimizer = DGCMomentum(
            learning_rate=optimizer._learning_rate,
            momentum=optimizer._momentum,
            parameters=optimizer._parameter_list,
            sparsity=cfg.get("sparsity", [0.999])[-1]
            if isinstance(cfg.get("sparsity"), (list, tuple))
            else cfg.get("sparsity", 0.999),
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            grad_clip=optimizer._grad_clip)
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", None) or {}
        optimizer = LocalSGDOptimizer(optimizer,
                                      k_steps=cfg.get("k_steps", 1))
    return optimizer


class GradientMergeHelper:
    """Accumulate k_steps of grads before one optimizer apply
    (reference: meta_optimizers/gradient_merge_optimizer.py — the
    GradientMerge pass adds gradient-accumulate blocks to the program).

    Usage (inside HybridParallelOptimizer.step): ``if helper.accumulate(
    params): return`` — returns True while still accumulating; on the
    k-th call it installs the merged (optionally averaged) grads on the
    params and returns False so the caller applies the inner step.
    """

    def __init__(self, k_steps, avg=True):
        self.k_steps = max(int(k_steps), 1)
        self.avg = bool(avg)
        self._count = 0
        self._buf = {}

    def accumulate(self, params):
        if self.k_steps <= 1:
            return False
        self._count += 1
        for p in params:
            g = p._grad
            if g is None:
                continue
            acc = self._buf.get(id(p))
            self._buf[id(p)] = g if acc is None else acc + g
        if self._count % self.k_steps != 0:
            return True
        for p in params:
            acc = self._buf.pop(id(p), None)
            if acc is not None:
                p._grad = acc / self.k_steps if self.avg else acc
        return False


class LocalSGDOptimizer:
    """Periodic parameter averaging (reference:
    meta_optimizers/localsgd_optimizer.py — train k local steps, then
    allreduce-average the params instead of averaging grads each step).

    The inner optimizer steps on purely local grads; every ``k_steps``
    the params are averaged across the data-parallel group.  Inside a
    shard_map over the dp axis (per-device param copies) ``sync()`` is a
    real ``pmean``; in the replicated-GSPMD eager world it is an
    identity (grads are already averaged, i.e. sync is trivially true
    every step).  ``sync_values`` is the pure functional piece for
    compiled per-device training loops.
    """

    def __init__(self, inner, k_steps=1, group=None):
        self._inner = inner
        self.k_steps = max(int(k_steps), 1)
        self._group = group
        self._local_steps = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        self._local_steps += 1
        if self._local_steps % self.k_steps == 0:
            self.sync()

    def sync(self):
        from ...collective import all_reduce, ReduceOp
        params = self._inner._parameter_list or []
        for p in params:
            all_reduce(p, op=ReduceOp.AVG, group=self._group)

    @staticmethod
    def sync_values(param_values, axis_name):
        """Pure pmean over the dp axis for shard_map training loops."""
        from jax import lax
        return [lax.pmean(v, axis_name) for v in param_values]

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)
