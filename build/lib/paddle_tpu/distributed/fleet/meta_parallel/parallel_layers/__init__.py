from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy)
from .random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed)
