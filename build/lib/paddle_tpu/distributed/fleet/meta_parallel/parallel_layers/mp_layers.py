"""Megatron-style tensor-parallel layers (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py
— Column/RowParallelLinear, VocabParallelEmbedding, ParallelCrossEntropy
built on c_identity/c_allreduce/c_concat comm ops + per-rank weight slices).

TPU-native design: no per-rank slices and no hand-inserted collectives.
Each layer holds the FULL logical weight annotated with a ``pspec`` over
the "model" mesh axis; the PlacementPlan device_puts it sharded, and XLA's
SPMD partitioner inserts exactly the Megatron communication pattern:

- ColumnParallelLinear  W:(in, out) sharded (None, "model") → local matmul,
  activations sharded on the feature dim (the c_identity fwd is free).
- RowParallelLinear     W:(in, out) sharded ("model", None) → local matmul
  + psum of partial sums (the reference's mp_allreduce).
- VocabParallelEmbedding weight (vocab, hidden) sharded ("model", None) →
  partitioned gather + psum of masked lookups.
- ParallelCrossEntropy: softmax-CE over logits sharded on the class dim —
  XLA lowers max/sum reductions to the per-shard + psum pattern of the
  reference's c_softmax_with_cross_entropy CUDA kernel.

``gather_output`` / ``input_is_parallel`` control activation shardings via
with_sharding_constraint, mirroring the reference's flags.
"""
import math

import jax
import jax.numpy as jnp

from .....framework.core import Tensor
from .....framework.autograd import call_op
from ..... import nn
from .....nn import functional as F

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_axis(mp_group=None):
    """The mesh axis name TP rides on."""
    return "model"


def _constraint(value, spec):
    """Apply with_sharding_constraint if a mesh is active (inside pjit with
    a plan mesh); otherwise a no-op (eager single-device)."""
    try:
        from jax.sharding import PartitionSpec as P
        return jax.lax.with_sharding_constraint(value, P(*spec))
    except Exception:
        return value


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self._axis = _mp_axis(mp_group)
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            dtype=None, is_bias=False)
        self.weight.pspec = (None, self._axis)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
            self.bias.pspec = (self._axis,)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # reference: c_concat across mp group → replicated activation
            spec = [None] * len(out.shape)
            out = call_op(lambda v: _constraint(v, spec), out)
        else:
            spec = [None] * (len(out.shape) - 1) + [self._axis]
            out = call_op(lambda v: _constraint(v, spec), out)
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self._axis = _mp_axis(mp_group)
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            is_bias=False)
        self.weight.pspec = (self._axis, None)
        self.weight.is_distributed = True
        if has_bias:
            # bias applies AFTER the psum → replicated
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (len(x.shape) - 1) + [self._axis]
            x = call_op(lambda v: _constraint(v, spec), x)
        out = F.linear(x, self.weight)   # XLA: local matmul + psum
        spec = [None] * len(out.shape)
        out = call_op(lambda v: _constraint(v, spec), out)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            is_bias=False)
        self.weight.pspec = (self._axis, None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross-entropy (reference:
    c_softmax_with_cross_entropy op).  Computed directly on class-dim
    sharded logits; the partitioner emits per-shard max/sum + psum."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
