"""TP RNG state tracker (reference: fleet/meta_parallel/parallel_layers/
random.py — get_rng_state_tracker with model-parallel vs global seeds so
dropout inside TP regions differs per rank while replicated regions agree).

TPU-native: JAX keys are functional, so "states" are named base keys;
``rng_state(name)`` folds the named key into the active rng scope.  Under
GSPMD there is one program, so per-shard decorrelation of sharded dropout
masks happens by construction (each device generates its slice of the same
logical mask); the tracker's job reduces to deterministic, name-keyed
streams — kept API-compatible.
"""
from contextlib import contextmanager

import jax

from .....framework import random as _random

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = jax.random.key(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            # auto-register with a name-derived seed (reference raises; we
            # are permissive because there's no cross-rank state to desync)
            self.add(name, abs(hash(name)) % (2 ** 31))
        key = self.states_[name]
        with _random.rng_scope(key):
            yield
        # advance the named stream so successive uses differ
        self.states_[name] = jax.random.fold_in(key, 1)


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _TRACKER


def model_parallel_random_seed(seed=None):
    import paddle_tpu as paddle
    global_seed = seed if seed is not None else 0
    _TRACKER.reset()
    paddle.seed(global_seed)
    _TRACKER.add(MODEL_PARALLEL_RNG, global_seed + 1024)
