"""PipelineLayer API (reference: fleet/meta_parallel/parallel_layers/
pp_layers.py — LayerDesc/SharedLayerDesc declarative stage spec,
segmentation by layer count / "uniform" / custom cut, per-stage
materialization).

TPU-native: the declarative spec is kept verbatim; "segmentation" maps the
homogeneous middle run onto the stacked SPMD pipeline
(distributed/pipeline.py), with the in-homogeneous head/tail run outside
the rotation loop.  There is no per-rank materialization — every process
holds the full logical model; the pipe mesh axis holds the *shards*.
"""
import math

from ....nn.layer.layers import Layer, LayerList, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("The input of LayerDesc should be Layer")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (e.g. tied embedding/LM head).
    In the single-program design sharing is literal object reuse."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Reference API: PipelineLayer(layers=[descs...], num_stages=...,
    loss_fn=..., seg_method="uniform").  forward runs the full model (one
    SPMD program); ``segment`` exposes the stage cut points;
    ``staged_module(mesh)`` builds the stacked SPMD pipeline over the
    homogeneous middle segment when one exists.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layer_descs = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._num_virtual_stages = int(num_virtual_pipeline_stages or 1)
        self._shared = {}

        built = []
        for d in self._layer_descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, "func"))
            else:
                raise TypeError(f"bad pipeline item {d}")
        self.run_function = built
        self._layers_list = LayerList(
            [l for l, tag in built if isinstance(l, Layer)])

    @property
    def parameters_list(self):
        return self._layers_list

    def get_num_stages(self):
        return self._num_stages

    def segment(self):
        """Stage cut points over the layer list.  seg_method:
        - "uniform": equal-count split of all items;
        - "layer:<Class>": boundaries fall only at instances of <Class>,
          distributing those instances evenly — items before the first
          instance join stage 0, trailing items join the last stage
          (reference segment_by_layer semantics)."""
        n = len(self.run_function)
        S = self._num_stages
        if isinstance(self._seg_method, str) and \
                self._seg_method.startswith("layer:"):
            cls_name = self._seg_method.split(":", 1)[1]
            idxs = [i for i, (l, _) in enumerate(self.run_function)
                    if type(l).__name__ == cls_name]
            if not idxs:
                raise ValueError(
                    f"seg_method {self._seg_method!r}: no layer of class "
                    f"{cls_name!r} in the pipeline")
            if len(idxs) < S:
                raise ValueError(
                    f"seg_method {self._seg_method!r}: {len(idxs)} "
                    f"{cls_name} layers < {S} stages")
            counts = [len(idxs) // S + (1 if k < len(idxs) % S else 0)
                      for k in range(S)]
            cuts, acc = [0], 0
            for k in range(S - 1):
                acc += counts[k]
                cuts.append(idxs[acc])
            cuts.append(n)
            return cuts
        per = int(math.ceil(n / S))
        cuts = [min(i * per, n) for i in range(S + 1)]
        cuts[-1] = n
        return cuts

    def forward(self, x, *args, **kwargs):
        for layer, tag in self.run_function:
            if tag == "func":
                x = layer(x)
            elif tag is not None and tag != "func" and callable(tag):
                x = tag(self._shared_for(layer), x)
            else:
                x = layer(x)
        return x

    def _shared_for(self, layer):
        return layer

    def _homogeneous_span(self):
        """(start, end) of the longest run of structurally identical
        parameterized layers in run_function (the pipelineable middle);
        (0, 0) when none."""
        sigs = []
        for l, _ in self.run_function:
            if isinstance(l, Layer):
                sigs.append((type(l).__name__, tuple(
                    tuple(p.shape) for _, p in l.named_parameters())))
            else:
                sigs.append(("func", None))
        best, cur, bstart = 0, 1, 0
        for i in range(1, len(sigs)):
            if sigs[i] == sigs[i - 1] and sigs[i][1]:
                cur += 1
                if cur > best:
                    best, bstart = cur, i - cur + 1
            else:
                cur = 1
        if best < 2:
            return 0, 0
        return bstart, bstart + best

    def homogeneous_run(self):
        """(head_layers, middle_blocks, tail_layers) where middle_blocks
        are structurally identical (the pipelineable run)."""
        items = [l for l, _ in self.run_function]
        start, end = self._homogeneous_span()
        if start == end:
            return items, [], []
        return items[:start], items[start:end], items[end:]

    def staged_module(self, mesh, axis="pipe", remat=None):
        from ...pipeline import PipelineStagedModule
        _, mid, _ = self.homogeneous_run()
        if not mid:
            raise ValueError("no homogeneous block run to pipeline")
        if remat is None:
            remat = self._recompute_interval > 0
        return PipelineStagedModule(mid, mesh, axis=axis, remat=remat,
                                    n_virtual=self._num_virtual_stages)
