"""Fleet utils (reference: python/paddle/distributed/fleet/utils/)."""
from .recompute import recompute, recompute_sequential  # noqa: F401


def fused_allreduce_gradients(parameter_list, hcg):
    """Under GSPMD the DP grad reduction happens inside the compiled step;
    eager multi-process fallback averages via process_allgather."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    for p in parameter_list:
        if p._grad is not None:
            g = multihost_utils.process_allgather(p._grad)
            p._grad = g.mean(axis=0)
