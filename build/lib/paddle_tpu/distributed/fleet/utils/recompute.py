"""Activation recompute (reference:
python/paddle/distributed/fleet/recompute/recompute.py — replay forward in
backward with preserved RNG).

TPU-native: ``jax.checkpoint`` (rematerialization) IS this feature, with
RNG determinism free because our dropout keys are functional.  In eager
mode we run the function through one tape node whose vjp re-runs the
forward under jax.checkpoint semantics.
"""
import jax

from ....framework.core import Tensor
from ....framework import autograd as _ag
from ....framework.random import rng_scope, next_key


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    other = [(i, a) for i, a in enumerate(args)
             if not isinstance(a, Tensor)]
    tpos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    key = next_key()

    def pure(*vals):
        full = [None] * len(args)
        for i, a in other:
            full[i] = a
        for i, v in zip(tpos, vals):
            full[i] = Tensor(v)
        with _ag.suspend_tape(), rng_scope(key):
            out = function(*full, **kwargs)
        return jax.tree.map(
            lambda o: o._value if isinstance(o, Tensor) else o, out,
            is_leaf=lambda o: isinstance(o, Tensor))

    ck = jax.checkpoint(pure)
    return _ag.call_op(lambda *vs: ck(*vs), *tensor_args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Recompute over a Sequential in segments (reference:
    recompute_sequential / recompute_hybrid)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    seg_size = max(1, n // max(segments, 1))
    out = args
    for s in range(0, n, seg_size):
        chunk = layers[s:s + seg_size]

        def seg_fn(*xs, _chunk=chunk):
            y = xs if len(xs) > 1 else xs[0]
            for l in _chunk:
                y = l(y) if not isinstance(y, tuple) else l(*y)
            return y
        out = recompute(seg_fn, *(out if isinstance(out, tuple) else (out,)))
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if len(out) == 1 else out
