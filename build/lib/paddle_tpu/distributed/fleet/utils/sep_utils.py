"""Segment-parallel ("sep") long-context attention utilities.

Reference analogue: the ``sep`` mesh axis in
python/paddle/distributed/fleet/base/topology.py — the reference's in-core
support is the axis + alltoall reshard (Ulysses); ring attention is made
first-class here per SURVEY.md §5.7/§7.

Two modes over the same seq-sharded activations (B, S/sep, H, D):
- ``sep_attention(..., mode="ulysses")`` — all_to_all head<->seq reshard
  around dense/flash attention (needs sep | num_heads).
- ``sep_attention(..., mode="ring")`` — ppermute KV rotation with online
  softmax (any head count, O(S/sep) activation memory).

These are Tensor-level and autograd-aware (jax differentiates through
ppermute/all_to_all); they must run inside a sep-axis shard_map — the
`RingFlashAttention` / `sep` paths of the hybrid engine arrange that.
"""
from ....framework.core import Tensor
from ....framework.autograd import call_op
from ....ops.ring_attention import ring_flash_attention, ulysses_attention

__all__ = ["sep_attention", "ring_attention", "split_inputs_sequence_dim",
           "RingFlashAttention"]

_SEP_AXIS = "sep"


def sep_attention(query, key, value, is_causal=False, mode="ring",
                  sep_axis=_SEP_AXIS, scale=None):
    """Sequence-parallel scaled-dot-product attention on seq-sharded
    (B, S_local, H, D) tensors; full-softmax-exact over the global S."""
    q, k, v = [t if isinstance(t, Tensor) else Tensor(t)
               for t in (query, key, value)]
    if mode == "ring":
        fn = lambda a, b, c: ring_flash_attention(
            a, b, c, sep_axis, causal=bool(is_causal), scale=scale)
    elif mode == "ulysses":
        fn = lambda a, b, c: ulysses_attention(
            a, b, c, sep_axis, causal=bool(is_causal), scale=scale)
    else:
        raise ValueError(f"unknown sep attention mode {mode!r}")
    return call_op(fn, q, k, v)


def ring_attention(query, key, value, is_causal=False, sep_axis=_SEP_AXIS):
    return sep_attention(query, key, value, is_causal, "ring", sep_axis)


def split_inputs_sequence_dim(inputs, rank, degree, axis=1):
    """Shard a full-sequence batch for this sep rank (the reference splits
    inputs along seq before feeding sep-parallel models)."""
    from ....tensor.manipulation import split
    if degree <= 1:
        return inputs
    return split(inputs, degree, axis=axis)[rank]


class RingFlashAttention:
    """PyLayer-shaped facade matching the reference-era custom-op API."""

    @staticmethod
    def apply(q, k, v, causal=False, sep_axis=_SEP_AXIS):
        return sep_attention(q, k, v, is_causal=causal, mode="ring",
                             sep_axis=sep_axis)
