"""Megatron sequence parallelism (reference:
python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
ScatterOp/GatherOp autograd pairs + Column/RowSequenceParallelLinear that
turn TP's activation allreduce into allgather+reduce_scatter and shard
layernorm/dropout activations along the sequence dim).

TPU-native: SP is a *sharding constraint* on the sequence dim over the
"model" axis.  Annotating the activations seq-sharded between the TP
matmuls makes XLA's partitioner produce the identical
allgather/reduce-scatter wire pattern — chosen by the compiler instead of
hand-written autograd pairs.  Ops keep the reference's names/API.
"""
import jax

from ....framework.autograd import call_op
from .... import nn
from ....nn import functional as F
from ..meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, _constraint)

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]

_AXIS = "model"


def _seq_dim(ndim):
    # activations are (seq, batch, hidden) in the reference's SP region;
    # we constrain dim 0 for 3D and dim 1 for (batch, seq, hidden) callers
    return 0


class ScatterOp:
    """Full → seq-sharded (fwd identity/slice, bwd allgather)."""

    @staticmethod
    def apply(x, axis=0):
        spec = [None] * len(x.shape)
        spec[axis] = _AXIS
        return call_op(lambda v: _constraint(v, spec), x)


class GatherOp:
    """seq-sharded → full (fwd allgather, bwd slice)."""

    @staticmethod
    def apply(x, axis=0):
        spec = [None] * len(x.shape)
        return call_op(lambda v: _constraint(v, spec), x)


class AllGatherOp:
    """seq-sharded → full with reduce-scatter backward (SP's matmul input
    gather; the partitioner picks the rs-backward automatically)."""

    @staticmethod
    def apply(x, axis=0):
        return GatherOp.apply(x, axis)


class ReduceScatterOp:
    """partial-sum full → seq-sharded reduced output."""

    @staticmethod
    def apply(x, axis=0):
        return ScatterOp.apply(x, axis)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, gather_output=False,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        # input arrives seq-sharded; gather (XLA: all-gather) then local
        # column matmul → feature-sharded out
        x = AllGatherOp.apply(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, input_is_parallel=True,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (len(x.shape) - 1) + [self._axis]
            x = call_op(lambda v: _constraint(v, spec), x)
        out = F.linear(x, self.weight)
        # reduce-scatter onto the seq dim instead of full allreduce
        out = ReduceScatterOp.apply(out)
        if self.bias is not None:
            out = out + self.bias
        return out


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(layer, *args, **kwargs):
    """Reference registers grad allreduce hooks for SP params (layernorm
    weights etc.).  Under GSPMD those gradients are reduced by the
    partitioner as part of the compiled backward — nothing to register."""
    return None
