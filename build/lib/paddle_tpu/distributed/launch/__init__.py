from .main import main  # noqa: F401
