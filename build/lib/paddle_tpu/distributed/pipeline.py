"""SPMD pipeline parallelism (reference: fleet/meta_parallel/
pipeline_parallel.py + pp_utils/p2p_communication.py — per-rank processes
exchanging activations via send_v2/recv_v2 under a 1F1B schedule, plus the
C++ FleetExecutor interceptor runtime for static graphs).

TPU-native design: ONE SPMD program.  The homogeneous transformer blocks
are stacked on a leading layer dim, sharded over the "pipe" mesh axis
(each device holds its stage's blocks); a `lax.scan` over ticks rotates
micro-batch activations stage→stage with `lax.ppermute` (the ICI-native
send/recv).  The classic fill/steady/drain schedule emerges from the scan:
tick t runs stage s on micro-batch (t-s) — exactly GPipe's wavefront; with
jax.checkpoint on the block, backward replays per (stage, microbatch) and
XLA's liveness keeps ~one microbatch of activations per stage live at a
time, giving 1F1B's memory profile without a hand-written scheduler.
Embedding/head run outside the loop (they are not stage-homogeneous).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["spmd_pipeline", "stack_block_params", "PipelineStagedModule"]


def _shard_map(fn, mesh, in_specs, out_specs, axis):
    try:
        from jax import shard_map  # jax >= 0.6 style
        # manual only over the pipe axis: other mesh axes (data/model/...)
        # stay under GSPMD so dp/tp compose with the pipeline
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False,
                         axis_names=frozenset({axis}))
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as sm
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def stack_block_params(param_lists):
    """[[block0 params...], [block1 params...]] → list of stacked arrays
    with leading dim L (blocks must be structurally identical)."""
    n = len(param_lists[0])
    return [jnp.stack([pl[i] for pl in param_lists], axis=0)
            for i in range(n)]


def spmd_pipeline(block_apply, stacked_params, x, mesh, axis="pipe",
                  remat=True, n_virtual=1):
    """Run L stacked blocks as an S-stage pipeline over micro-batches.

    block_apply(params_list, h) -> h'  — one block, pure.
    stacked_params: list of arrays with leading dim L (L % (S*V) == 0).
    x: (M, mb, ...) micro-batched activations, replicated on `axis`.
    Returns (M, mb, ...) outputs.

    ``n_virtual`` > 1 is the interleaved virtual-pipeline schedule
    (reference: PipelineParallelWithInterleave): physical stage s hosts
    the V non-contiguous logical stages {s, s+S, ..., s+(V-1)S}, and each
    activation makes V trips around the ppermute ring (a v counter rides
    the rotation).  Injection is continuous: micro-batch m enters stage 0
    at tick (m//S)·SV + (m%S) — exactly the slot where an activation that
    finished its last trip leaves the ring — so consecutive waves overlap
    with no inter-ring drain.  Per tick a stage runs L/(SV) layers, and
    the whole schedule takes ((M-1)//S)·SV + (M-1)%S + SV ticks: for
    M ≤ S that is (S-1) idle ticks spread over M·V+S-1 — the reference
    interleave's bubble shrink — without a hand-written scheduler.  The
    V=1 case reduces to the plain GPipe wavefront (M+S-1 ticks).
    """
    S = mesh.shape[axis]
    M = x.shape[0]
    V = int(n_virtual or 1)
    L = stacked_params[0].shape[0]
    assert L % (S * V) == 0, \
        f"layers {L} not divisible by stages*virtual {S}*{V}"
    per = L // (S * V)
    SV = S * V
    # logical stage l = v*S + s owns layers [l*per, (l+1)*per): reshape to
    # (V, S, per, ...) then put the physical-stage dim first for sharding
    params_s = [jnp.moveaxis(p.reshape(V, S, per, *p.shape[1:]), 1, 0)
                for p in stacked_params]

    if remat:
        block_apply = jax.checkpoint(block_apply)

    p_specs = [P(axis, *([None] * (p.ndim - 1))) for p in params_s]
    x_spec = P(*([None] * x.ndim))

    def run(params_l, xl):
        s_idx = lax.axis_index(axis)
        my_params = [p[0] for p in params_l]   # (V, per, ...)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def stage_compute(h, v):
            chunk = [lax.dynamic_index_in_dim(p, jnp.clip(v, 0, V - 1), 0,
                                              keepdims=False)
                     for p in my_params]        # (per, ...)

            def body(carry, blk):
                return block_apply(blk, carry), None
            h, _ = lax.scan(body, h, chunk)
            return h

        state0 = jnp.zeros_like(xl[0])
        out0 = jnp.zeros_like(xl)
        v0 = jnp.zeros((), jnp.int32)

        def tick(carry, t):
            state, v, outputs = carry
            # stage 0 injects micro-batch m at tick (m//S)*SV + (m%S);
            # live wrap-arounds land on phases >= S, dead ones (v == V)
            # land exactly on the injection phases and are replaced
            phase = t % SV
            m_in = (t // SV) * S + phase
            inject = (s_idx == 0) & (phase < S) & (m_in < M)
            mb_in = lax.dynamic_index_in_dim(
                xl, jnp.clip(m_in, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(inject, mb_in, state)
            v_cur = jnp.where(inject, 0, v)
            out = stage_compute(inp, v_cur)
            # micro-batch m completes at its inject tick + SV - 1
            u = t - (SV - 1)
            uphase = u % SV
            m_out = (u // SV) * S + uphase
            write = (s_idx == S - 1) & (v_cur == V - 1) & (u >= 0) \
                & (uphase < S) & (m_out < M)
            out_idx = jnp.clip(m_out, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, out, cur), out_idx, 0)
            state = lax.ppermute(out, axis, perm)
            # the v counter rides the ring; +1 on the S-1 → 0 wrap
            v = lax.ppermute(
                v_cur + (s_idx == S - 1).astype(jnp.int32), axis, perm)
            return (state, v, outputs), None

        n_ticks = ((M - 1) // S) * SV + (M - 1) % S + SV
        (_, _, outputs), _ = lax.scan(tick, (state0, v0, out0),
                                      jnp.arange(n_ticks))
        # only the last stage holds real outputs; replicate via psum
        outputs = jnp.where(s_idx == S - 1, outputs, 0)
        return lax.psum(outputs, axis)

    fn = _shard_map(run, mesh, in_specs=(p_specs, x_spec),
                    out_specs=x_spec, axis=axis)
    return fn(params_s, x)


class PipelineStagedModule:
    """Bridge from a Layer holding N identical blocks to spmd_pipeline.

    Captures the blocks' parameters (functional seam), stacks them, and
    exposes ``apply(stacked_values, x_microbatches)``.
    """

    def __init__(self, blocks, mesh, axis="pipe", remat=True, n_virtual=1):
        from ..framework.core import Tensor
        from ..framework import autograd as _ag
        self.blocks = list(blocks)
        self.mesh = mesh
        self.axis = axis
        self.remat = remat
        self.n_virtual = int(n_virtual or 1)
        self.template = self.blocks[0]
        self.t_params = [p for _, p in self.template.named_parameters()]
        self.param_lists = [[p._value for _, p in b.named_parameters()]
                            for b in self.blocks]
        self.stacked = stack_block_params(self.param_lists)

        template, t_params = self.template, self.t_params

        def block_apply(blk_values, h):
            olds = [p._value for p in t_params]
            for p, v in zip(t_params, blk_values):
                p._value = v
            try:
                with _ag.suspend_tape():
                    return template(Tensor(h))._value
            finally:
                for p, v in zip(t_params, olds):
                    p._value = v
        self.block_apply = block_apply

    def apply(self, stacked_values, x_mb):
        return spmd_pipeline(self.block_apply, stacked_values, x_mb,
                             self.mesh, self.axis, remat=self.remat,
                             n_virtual=self.n_virtual)
