"""paddle.distributed.rpc (reference: python/paddle/distributed/rpc/rpc.py
over the C++ brpc agent in paddle/fluid/distributed/rpc/).

TPU-native: control-plane RPC stays host-side Python — a threaded TCP
server per worker executing pickled callables, with worker discovery
through the framework TCPStore (the reference exchanges WorkerInfo through
its master the same way).  Trust model matches the reference (pickled
payloads on a private cluster network); tensor traffic belongs on the XLA
collective path, not here.
"""
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor

from ..store import TCPStore

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "get_current_worker_info"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state = {}


def _recv_full(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            (size,) = struct.unpack("!Q", _recv_full(self.request, 8))
            fn, args, kwargs = pickle.loads(_recv_full(self.request, size))
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:          # ship the exception back
                result = (False, e)
            try:
                payload = pickle.dumps(result, protocol=4)
            except Exception as e:          # unpicklable result/exception
                payload = pickle.dumps(
                    (False, RuntimeError(
                        f"rpc result not picklable: {e!r}; original: "
                        f"{result[1]!r}")), protocol=4)
            self.request.sendall(struct.pack("!Q", len(payload)) + payload)
        except ConnectionError:
            pass


class _RpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC agent and exchange WorkerInfo via the store
    (reference: paddle.distributed.rpc.init_rpc)."""
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", 0))
    world_size = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:8765")
    host, port = master_endpoint.rsplit(":", 1)

    server = _RpcServer(("0.0.0.0", 0), _RpcHandler)
    sport = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    # reuse an already-running store at the endpoint (e.g. launcher-hosted);
    # otherwise rank 0 hosts it and the rest retry until it is up
    store = None
    deadline = time.time() + 60.0
    while store is None:
        try:
            store = TCPStore(host, int(port), is_master=False,
                             world_size=world_size, timeout=2.0)
        except Exception:
            if rank == 0:
                store = TCPStore(host, int(port), is_master=True,
                                 world_size=world_size)
            elif time.time() > deadline:
                raise TimeoutError(
                    f"rpc master store at {master_endpoint} never came up")
            else:
                time.sleep(0.5)
    my_ip = os.environ.get("POD_IP", "127.0.0.1")
    store.set(f"rpc/worker/{rank}",
              pickle.dumps(WorkerInfo(name, rank, my_ip, sport)))
    infos = {}
    for r in range(world_size):
        infos[r] = pickle.loads(store.get(f"rpc/worker/{r}", timeout=60.0))
    by_name = {w.name: w for w in infos.values()}

    _state.update(dict(server=server, thread=thread, store=store,
                       rank=rank, world_size=world_size, name=name,
                       infos=infos, by_name=by_name,
                       pool=ThreadPoolExecutor(max_workers=8)))
    # everybody present before returning (reference barriers in init_rpc)
    store.barrier("rpc/init", world_size=world_size)
    return infos[rank]


def _resolve(to):
    if isinstance(to, WorkerInfo):
        return to
    if isinstance(to, int):
        return _state["infos"][to]
    return _state["by_name"][to]


def _invoke(to, fn, args, kwargs, timeout):
    w = _resolve(to)
    payload = pickle.dumps((fn, args or (), kwargs or {}), protocol=4)
    with socket.create_connection((w.ip, w.port),
                                  timeout=None if timeout in (-1, None)
                                  else timeout) as s:
        s.sendall(struct.pack("!Q", len(payload)) + payload)
        (size,) = struct.unpack("!Q", _recv_full(s, 8))
        ok, result = pickle.loads(_recv_full(s, size))
    if not ok:
        raise result
    return result


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    if "server" not in _state:
        raise RuntimeError("call init_rpc first")
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1):
    if "server" not in _state:
        raise RuntimeError("call init_rpc first")
    return _state["pool"].submit(_invoke, to, fn, args, kwargs, timeout)


def get_worker_info(name):
    return _state["by_name"][name]


def get_all_worker_infos():
    return [w for _, w in sorted(_state["infos"].items())]


def get_current_worker_info():
    return _state["infos"][_state["rank"]]


def shutdown():
    if "server" not in _state:
        return
    # drain own outgoing calls first, THEN barrier so no peer is mid-call
    # against our server when we close it
    _state["pool"].shutdown(wait=True)
    try:
        _state["store"].barrier("rpc/shutdown",
                                world_size=_state["world_size"])
    except Exception:
        pass
    _state["server"].shutdown()
    _state["server"].server_close()
    _state.clear()
