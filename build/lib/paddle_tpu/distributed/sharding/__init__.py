"""ZeRO / GroupSharded (reference: python/paddle/distributed/sharding/
group_sharded.py + fleet/meta_parallel/sharding/).

TPU-native: ZeRO stages are *shardings*, not wrapper protocols —
- stage 1 (os):      optimizer state sharded on the fsdp axis
- stage 2 (os_g):    + gradients reduce-scattered (psum_scatter)
- stage 3 (p_g_os):  + parameters sharded, all-gathered per-layer on use
XLA inserts the gathers/scatters from NamedSharding annotations; the
wrapper records the chosen level so the engine (hapi/fleet train step)
builds shardings accordingly.  M2 wires the engine integration.
"""
from ...nn.layer.layers import Layer

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "GroupShardedStage3Marker"]


class _GroupShardedModel(Layer):
    def __init__(self, model, level, offload=False):
        super().__init__()
        self._layers = model
        self.sharding_level = level
        self.offload = offload
        import jax
        if jax.device_count() > 1:
            from ..engine import make_data_parallel_plan
            self._placement_plan = make_data_parallel_plan(level=level)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


GroupShardedStage3Marker = _GroupShardedModel


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Returns (model, optimizer, scaler) with sharding level recorded.

    level: 'os' | 'os_g' | 'p_g_os' (ZeRO-1/2/3).
    """
    assert level in ("os", "os_g", "p_g_os"), f"bad level {level}"
    wrapped = _GroupShardedModel(model, level, offload)
    optimizer.sharding_level = level
    return wrapped, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ...framework.io import save
    inner = model._layers if isinstance(model, _GroupShardedModel) else model
    os.makedirs(output, exist_ok=True)
    save(inner.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
