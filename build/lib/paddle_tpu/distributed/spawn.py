"""paddle.distributed.spawn (reference: python/paddle/distributed/spawn.py
— fork N workers with per-rank PADDLE_* env for single-node tests and
notebooks).

TPU-native notes: on TPU one process drives all local chips (SPMD), so
``nprocs>1`` is the CPU-collective test path (the reference's Gloo story):
children are started with the ``spawn`` start method and rank env set
before import, and rendezvous through PADDLE_MASTER.  nprocs==1 runs
inline — sharding, not processes, is the parallelism on-device.
"""
import os
import socket

__all__ = ["spawn", "MultiprocessContext"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_entry(rank, nprocs, master, base_port, env_extra, func, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_LOCAL_RANK"] = str(rank)
    os.environ["PADDLE_MASTER"] = master
    os.environ["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{base_port + rank}"
    for k, v in (env_extra or {}).items():
        os.environ[k] = str(v)
    func(*args)


class MultiprocessContext:
    def __init__(self, processes):
        self.processes = processes

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        for rank, p in enumerate(self.processes):
            if p.is_alive():
                raise TimeoutError(
                    f"spawned worker {rank} still running after join("
                    f"timeout={timeout}) — terminate() it or wait longer")
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawned worker {rank} exited with code {p.exitcode}")
        return True

    def terminate(self):
        for p in self.processes:
            if p.is_alive():
                p.terminate()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Run ``func`` on ``nprocs`` workers (rank env pre-set).  nprocs<=1
    runs inline and returns None; otherwise returns a
    MultiprocessContext (joined first when ``join=True``)."""
    if nprocs in (-1, 0, 1):
        os.environ.setdefault("PADDLE_TRAINER_ID", "0")
        os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
        os.environ.setdefault("PADDLE_MASTER", "127.0.0.1:6768")
        func(*args)
        return None
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    master = f"127.0.0.1:{_free_port()}"
    # per-run trainer base port (like the master port): fixed 6170+rank
    # endpoints collide when two spawn() runs share the machine (e.g.
    # parallel test workers)
    base_port = _free_port()
    env_extra = dict(options.get("env", {}))
    # children must not grab the single-client TPU tunnel the parent may
    # hold: force CPU regardless of the parent's JAX_PLATFORMS; callers
    # can override via options={"env": {"JAX_PLATFORMS": ...}}
    env_extra.setdefault("JAX_PLATFORMS", "cpu")
    procs = []
    for rank in range(nprocs):
        # set env in the PARENT around start(): spawn children inherit it
        # at exec, so even module-import-time code in the child sees its
        # rank/platform (then _worker_entry re-asserts it)
        saved = {}
        child_env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_LOCAL_RANK": str(rank),
            "PADDLE_MASTER": master,
            "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{base_port + rank}",
            **{k: str(v) for k, v in env_extra.items()},
        }
        for k, v in child_env.items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            p = ctx.Process(
                target=_worker_entry,
                args=(rank, nprocs, master, base_port, env_extra, func,
                      tuple(args)),
                daemon=daemon)
            p.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        procs.append(p)
    context = MultiprocessContext(procs)
    if join:
        context.join()
    return context
