"""TCPStore — rank-0-hosted key-value rendezvous store (reference:
paddle/fluid/distributed/store/tcp_store.cc, exposed to Python as
``paddle.distributed.TCPStore``-alike via pybind).

Backed by the native C++ server/client in paddle_tpu/csrc/tcp_store.cc
(one connection-handler thread per worker, condition-variable-blocked
GET/WAIT).  A pure-Python implementation of the same wire protocol is the
fallback so behavior is identical without the toolchain.

On TPU the PJRT coordination service (jax.distributed) replaces NCCL
unique-id exchange; the store remains the framework's control plane for
barriers, elastic membership, and launcher rendezvous.
"""
import ctypes
import os
import socket
import socketserver
import struct
import threading
import time

from ..framework import native

__all__ = ["TCPStore", "MasterStore"]

_SET, _GET, _ADD, _WAIT, _DEL, _NUMKEYS = 1, 2, 3, 4, 5, 6


class _PyStoreServer:
    """Python fallback server speaking the native wire protocol."""

    def __init__(self, port=0):
        kv = {}
        cond = threading.Condition()

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    hdr = _recv_full(sock, 5)
                    if hdr is None:
                        return
                    op, keylen = struct.unpack("<BI", hdr)
                    key = _recv_full(sock, keylen) if keylen else b""
                    if key is None:
                        return
                    lenbuf = _recv_full(sock, 8)
                    if lenbuf is None:
                        return
                    (paylen,) = struct.unpack("<Q", lenbuf)
                    payload = _recv_full(sock, paylen) if paylen else b""
                    if payload is None:
                        return
                    status, out = 0, b""
                    if op == _SET:
                        with cond:
                            kv[key] = payload
                            cond.notify_all()
                    elif op in (_GET, _WAIT):
                        (timeout_ms,) = struct.unpack("<q", payload)
                        deadline = (None if timeout_ms < 0
                                    else time.monotonic() + timeout_ms / 1e3)
                        with cond:
                            while key not in kv and not outer._stopped:
                                rem = (None if deadline is None
                                       else deadline - time.monotonic())
                                if rem is not None and rem <= 0:
                                    break
                                cond.wait(rem)
                            if key in kv:
                                out = kv[key] if op == _GET else b""
                            else:
                                status = 1
                    elif op == _ADD:
                        (delta,) = struct.unpack("<q", payload)
                        with cond:
                            prev = kv.get(key, b"")
                            cur = (struct.unpack("<q", prev)[0]
                                   if len(prev) == 8 else 0) + delta
                            kv[key] = struct.pack("<q", cur)
                            out = kv[key]
                            cond.notify_all()
                    elif op == _DEL:
                        with cond:
                            status = 0 if kv.pop(key, None) is not None else 1
                    elif op == _NUMKEYS:
                        with cond:
                            out = struct.pack("<q", len(kv))
                    else:
                        status = 1
                    try:
                        sock.sendall(struct.pack("<BQ", status, len(out)) + out)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._stopped = False
        self._cond = cond
        self._server = Server(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._stopped = True
        with self._cond:  # wake handlers parked in infinite GET/WAIT
            self._cond.notify_all()
        self._server.shutdown()
        self._server.server_close()


def _recv_full(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _PyStoreClient:
    def __init__(self, host, port, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1e3
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(None)
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"TCPStore: cannot reach {host}:{port}")
                time.sleep(0.05)
        self._mu = threading.Lock()

    def request(self, op, key, payload):
        with self._mu:
            msg = struct.pack("<BI", op, len(key)) + key + \
                struct.pack("<Q", len(payload)) + payload
            self._sock.sendall(msg)
            hdr = _recv_full(self._sock, 9)
            if hdr is None:
                raise ConnectionError("TCPStore connection lost")
            status, outlen = struct.unpack("<BQ", hdr)
            out = _recv_full(self._sock, outlen) if outlen else b""
            return status, out

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class TCPStore:
    """Distributed KV store.  ``is_master=True`` also hosts the server.

    API mirrors the reference: set/get/add/wait/delete_key, plus a
    counter-based ``barrier``.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30.0):
        self._lib = native.get_lib()
        self._server = None
        self._server_h = None
        self.world_size = world_size
        timeout_ms = int(timeout * 1000)
        if is_master:
            if self._lib is not None:
                self._server_h = self._lib.pt_store_server_start(port)
                if not self._server_h:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = self._lib.pt_store_server_port(self._server_h)
            else:
                self._server = _PyStoreServer(port)
                port = self._server.port
            host = "127.0.0.1" if host in ("", "0.0.0.0") else host
        self.host, self.port = host, port
        if self._lib is not None:
            self._client = self._lib.pt_store_client_connect(
                host.encode(), port, timeout_ms)
            if not self._client:
                raise TimeoutError(f"TCPStore: cannot reach {host}:{port}")
        else:
            self._client = _PyStoreClient(host, port, timeout_ms)

    # -- core ops ---------------------------------------------------
    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        if self._lib is not None:
            buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
                if value else None
            rc = self._lib.pt_store_set(self._client, key.encode(), buf,
                                        len(value))
            if rc != 0:
                raise ConnectionError("TCPStore set failed")
        else:
            self._client.request(_SET, key.encode(), value)

    def get(self, key, timeout=30.0):
        tmo = int(timeout * 1000) if timeout is not None else -1
        if self._lib is not None:
            import ctypes
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.pt_store_get(self._client, key.encode(), tmo,
                                       ctypes.byref(out))
            if n == -1:
                raise KeyError(key)
            if n < 0:
                raise ConnectionError("TCPStore get failed")
            return native.take_buffer(self._lib, out, n)
        status, out = self._client.request(
            _GET, key.encode(), struct.pack("<q", tmo))
        if status != 0:
            raise KeyError(key)
        return out

    def add(self, key, delta=1):
        if self._lib is not None:
            v = self._lib.pt_store_add(self._client, key.encode(), delta)
            if v == -(2 ** 63):
                raise ConnectionError("TCPStore add failed")
            return v
        status, out = self._client.request(
            _ADD, key.encode(), struct.pack("<q", delta))
        if status != 0 or len(out) != 8:
            raise ConnectionError("TCPStore add failed")
        return struct.unpack("<q", out)[0]

    def wait(self, keys, timeout=30.0):
        if isinstance(keys, str):
            keys = [keys]
        tmo = int(timeout * 1000) if timeout is not None else -1
        for key in keys:
            if self._lib is not None:
                rc = self._lib.pt_store_wait(self._client, key.encode(), tmo)
                if rc == 1:
                    raise TimeoutError(f"TCPStore: wait({key}) timed out")
                if rc != 0:
                    raise ConnectionError("TCPStore wait failed")
            else:
                status, _ = self._client.request(
                    _WAIT, key.encode(), struct.pack("<q", tmo))
                if status != 0:
                    raise TimeoutError(f"TCPStore: wait({key}) timed out")

    def delete_key(self, key):
        if self._lib is not None:
            return self._lib.pt_store_delete(self._client, key.encode()) == 0
        status, _ = self._client.request(_DEL, key.encode(), b"")
        return status == 0

    def num_keys(self):
        if self._lib is not None:
            return self._lib.pt_store_num_keys(self._client)
        _, out = self._client.request(_NUMKEYS, b"", b"")
        return struct.unpack("<q", out)[0]

    # -- composite --------------------------------------------------
    def barrier(self, name="barrier", world_size=None, timeout=60.0):
        """Counter barrier: every rank adds 1, then waits for the release
        key that the last arriver sets."""
        n = world_size or self.world_size
        arrived = self.add(f"__{name}/count", 1)
        epoch = (arrived - 1) // n
        release = f"__{name}/release/{epoch}"
        if arrived % n == 0:
            self.set(release, b"1")
        self.wait([release], timeout=timeout)

    def close(self):
        if self._lib is not None:
            if self._client:
                self._lib.pt_store_client_close(self._client)
                self._client = None
            if self._server_h:
                self._lib.pt_store_server_stop(self._server_h)
                self._server_h = None
        else:
            if self._client is not None:
                self._client.close()
                self._client = None
            if self._server is not None:
                self._server.stop()
                self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def MasterStore(world_size, timeout=30.0):
    """Build the store from launcher env (PADDLE_MASTER,
    PADDLE_TRAINER_ID), rank 0 hosting."""
    master = os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, _, port = master.partition(":")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    return TCPStore(host or "127.0.0.1", int(port or 0), is_master=rank == 0,
                    world_size=world_size, timeout=timeout)
