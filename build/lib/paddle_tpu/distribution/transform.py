"""paddle.distribution.transform (reference:
python/paddle/distribution/transform.py — bijector library for
TransformedDistribution).

TPU-native: each Transform is a pair of jnp maps + a log-det-Jacobian, run
through the eager tape (``call_op``) so forward/inverse and
``TransformedDistribution.log_prob`` are differentiable and jit-safe.
"""
import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import call_op

__all__ = ["Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "IndependentTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


class Transform:
    _type = Type.OTHER

    def forward(self, x):
        return call_op(self._forward, _as_tensor(x))

    def inverse(self, y):
        return call_op(self._inverse, _as_tensor(y))

    def forward_log_det_jacobian(self, x):
        return call_op(self._fldj, _as_tensor(x))

    def inverse_log_det_jacobian(self, y):
        # default: -fldj(inverse(y))
        return call_op(lambda v: -self._fldj(self._inverse(v)),
                       _as_tensor(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # jnp-level implementations to override
    def _forward(self, v):
        raise NotImplementedError

    def _inverse(self, v):
        raise NotImplementedError

    def _fldj(self, v):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| (surjection onto [0, inf))."""
    _type = Type.SURJECTION

    def _forward(self, v):
        return jnp.abs(v)

    def _inverse(self, v):
        return v  # principal branch

    def _fldj(self, v):
        return jnp.zeros_like(v)


class AffineTransform(Transform):
    """y = loc + scale * x."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def _forward(self, v):
        return self.loc._value + self.scale._value * v

    def _inverse(self, v):
        return (v - self.loc._value) / self.scale._value

    def _fldj(self, v):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale._value)), v.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, v):
        return jnp.exp(v)

    def _inverse(self, v):
        return jnp.log(v)

    def _fldj(self, v):
        return v


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _as_tensor(power)

    def _forward(self, v):
        return jnp.power(v, self.power._value)

    def _inverse(self, v):
        return jnp.power(v, 1.0 / self.power._value)

    def _fldj(self, v):
        p = self.power._value
        return jnp.log(jnp.abs(p * jnp.power(v, p - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, v):
        return jax.nn.sigmoid(v)

    def _inverse(self, v):
        return jnp.log(v) - jnp.log1p(-v)

    def _fldj(self, v):
        return -jax.nn.softplus(-v) - jax.nn.softplus(v)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, v):
        return jnp.tanh(v)

    def _inverse(self, v):
        return jnp.arctanh(v)

    def _fldj(self, v):
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - v - jax.nn.softplus(-2.0 * v))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (surjection onto the simplex)."""
    _type = Type.OTHER

    def _forward(self, v):
        return jax.nn.softmax(v, axis=-1)

    def _inverse(self, v):
        return jnp.log(v)

    def _fldj(self, v):
        raise NotImplementedError("softmax is not injective; no log-det")


class StickBreakingTransform(Transform):
    """R^{K-1} → open simplex in R^K (reference:
    transform.StickBreakingTransform)."""
    _type = Type.BIJECTION

    def _forward(self, v):
        # y_k = z_k · prod_{j<k}(1-z_j),  z_k = sigmoid(x_k - log(K-k))
        offset = v.shape[-1] - jnp.arange(v.shape[-1], dtype=v.dtype)
        z = jax.nn.sigmoid(v - jnp.log(offset))
        cum = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate([jnp.ones_like(cum[..., :1]),
                                cum[..., :-1]], axis=-1)
        y = z * lead
        last = cum[..., -1:]
        return jnp.concatenate([y, last], axis=-1)

    def _inverse(self, v):
        y = v[..., :-1]
        rem = 1 - jnp.cumsum(y, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(rem[..., :1]), rem[..., :-1]], axis=-1)
        z = y / lead
        offset = y.shape[-1] - jnp.arange(y.shape[-1], dtype=v.dtype)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, v):
        # lower-triangular Jacobian: log|det| =
        # Σ_k [log z_k + log(1-z_k) + log Π_{j<k}(1-z_j)]
        offset = v.shape[-1] - jnp.arange(v.shape[-1], dtype=v.dtype)
        z = jax.nn.sigmoid(v - jnp.log(offset))
        cum = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate([jnp.ones_like(cum[..., :1]),
                                cum[..., :-1]], axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(lead), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else call_op(
                lambda a, b: a + b, total, ldj)
            x = t.forward(x)
        return total

    def inverse_log_det_jacobian(self, y):
        total = None
        for t in reversed(self.transforms):
            ildj = t.inverse_log_det_jacobian(y)
            total = ildj if total is None else call_op(
                lambda a, b: a + b, total, ildj)
            y = t.inverse(y)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Reinterpret trailing dims as event dims: sums the base log-det over
    the last ``reinterpreted_batch_rank`` axes."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = reinterpreted_batch_rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)
        r = self.rank
        return call_op(lambda v: jnp.sum(v, axis=tuple(range(-r, 0))), ldj)

    def inverse_log_det_jacobian(self, y):
        ildj = self.base.inverse_log_det_jacobian(y)
        r = self.rank
        return call_op(lambda v: jnp.sum(v, axis=tuple(range(-r, 0))), ildj)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, v):
        batch = v.shape[:v.ndim - len(self.in_event_shape)]
        return v.reshape(batch + self.out_event_shape)

    def _inverse(self, v):
        batch = v.shape[:v.ndim - len(self.out_event_shape)]
        return v.reshape(batch + self.in_event_shape)

    def _fldj(self, v):
        batch = v.shape[:v.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, v.dtype)

    def forward_shape(self, shape):
        n = len(shape) - len(self.in_event_shape)
        return tuple(shape[:n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(shape) - len(self.out_event_shape)
        return tuple(shape[:n]) + self.in_event_shape


class StackTransform(Transform):
    """Apply a list of transforms to slices along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def forward(self, x):
        x = _as_tensor(x)
        ax = self.axis

        def impl(v):
            parts = [t._forward(p.squeeze(ax)) for t, p in zip(
                self.transforms,
                jnp.split(v, len(self.transforms), axis=ax))]
            return jnp.stack(parts, axis=ax)
        return call_op(impl, x)

    def inverse(self, y):
        y = _as_tensor(y)
        ax = self.axis

        def impl(v):
            parts = [t._inverse(p.squeeze(ax)) for t, p in zip(
                self.transforms,
                jnp.split(v, len(self.transforms), axis=ax))]
            return jnp.stack(parts, axis=ax)
        return call_op(impl, y)

    def forward_log_det_jacobian(self, x):
        x = _as_tensor(x)
        ax = self.axis

        def impl(v):
            parts = [t._fldj(p.squeeze(ax)) for t, p in zip(
                self.transforms,
                jnp.split(v, len(self.transforms), axis=ax))]
            return jnp.stack(parts, axis=ax)
        return call_op(impl, x)

    def inverse_log_det_jacobian(self, y):
        y = _as_tensor(y)
        ax = self.axis

        def impl(v):
            parts = [-t._fldj(t._inverse(p.squeeze(ax))) for t, p in zip(
                self.transforms,
                jnp.split(v, len(self.transforms), axis=ax))]
            return jnp.stack(parts, axis=ax)
        return call_op(impl, y)
