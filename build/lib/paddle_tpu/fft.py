"""paddle.fft — discrete Fourier transforms (reference: python/paddle/fft.py
over phi fft kernels / cuFFT).  TPU-native: jnp.fft lowers to XLA's FFT HLO,
which runs on the TPU's vector unit; autograd comes from jax.vjp through the
eager tape like every other op.
"""
import jax.numpy as jnp

from .framework.core import Tensor
from .framework.autograd import call_op
from .tensor._helpers import ensure_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_VALID_NORM = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _VALID_NORM:
        raise ValueError(f"norm must be one of {_VALID_NORM}, got {norm!r}")
    return norm


def _1d(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        norm = _check_norm(norm)
        return call_op(lambda v: jfn(v, n=n, axis=axis, norm=norm),
                       ensure_tensor(x))
    return op


def _2d(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        norm = _check_norm(norm)
        return call_op(lambda v: jfn(v, s=s, axes=tuple(axes), norm=norm),
                       ensure_tensor(x))
    return op


def _nd(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        norm = _check_norm(norm)
        ax = tuple(axes) if axes is not None else None
        return call_op(lambda v: jfn(v, s=s, axes=ax, norm=norm),
                       ensure_tensor(x))
    return op


fft = _1d(jnp.fft.fft)
ifft = _1d(jnp.fft.ifft)
rfft = _1d(jnp.fft.rfft)
irfft = _1d(jnp.fft.irfft)
hfft = _1d(jnp.fft.hfft)
ihfft = _1d(jnp.fft.ihfft)

fft2 = _2d(jnp.fft.fft2)
ifft2 = _2d(jnp.fft.ifft2)


rfft2 = _2d(jnp.fft.rfft2)
irfft2 = _2d(jnp.fft.irfft2)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    norm = _check_norm(norm)
    return call_op(lambda v: _hfftn_impl(v, s, tuple(axes), norm),
                   ensure_tensor(x))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    norm = _check_norm(norm)
    return call_op(lambda v: _ihfftn_impl(v, s, tuple(axes), norm),
                   ensure_tensor(x))


fftn = _nd(jnp.fft.fftn)
ifftn = _nd(jnp.fft.ifftn)
rfftn = _nd(jnp.fft.rfftn)
irfftn = _nd(jnp.fft.irfftn)


def _default_axes(v, s, axes):
    """numpy/paddle semantics: axes=None means all axes when s is None,
    else the LAST len(s) axes."""
    if axes is not None:
        return tuple(axes)
    if s is None:
        return tuple(range(v.ndim))
    return tuple(range(v.ndim - len(s), v.ndim))


def _hfftn_impl(v, s, axes, norm):
    """N-d Hermitian FFT: complex-conjugate-symmetric input → real output.

    Last transformed axis uses hfft (expand hermitian half-spectrum); the
    leading axes are ordinary ffts of a (real) result, matching numpy's
    definition hfftn(x) = fftn over leading axes then hfft on the last.
    """
    axes = _default_axes(v, s, axes)
    s = list(s) if s is not None else [None] * len(axes)
    lead_axes, last_axis = axes[:-1], axes[-1]
    if lead_axes:
        lead_s = [n for n in s[:-1]]
        if any(n is not None for n in lead_s):
            v = jnp.fft.fftn(v, s=lead_s, axes=lead_axes, norm=norm)
        else:
            v = jnp.fft.fftn(v, axes=lead_axes, norm=norm)
    return jnp.fft.hfft(v, n=s[-1], axis=last_axis, norm=norm)


def _ihfftn_impl(v, s, axes, norm):
    axes = _default_axes(v, s, axes)
    s = list(s) if s is not None else [None] * len(axes)
    lead_axes, last_axis = axes[:-1], axes[-1]
    out = jnp.fft.ihfft(v, n=s[-1], axis=last_axis, norm=norm)
    if lead_axes:
        lead_s = s[:-1]
        if any(n is not None for n in lead_s):
            out = jnp.fft.ifftn(out, s=lead_s, axes=lead_axes, norm=norm)
        else:
            out = jnp.fft.ifftn(out, axes=lead_axes, norm=norm)
    return out


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    norm = _check_norm(norm)
    ax = tuple(axes) if axes is not None else None
    return call_op(lambda v: _hfftn_impl(v, s, ax, norm), ensure_tensor(x))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    norm = _check_norm(norm)
    ax = tuple(axes) if axes is not None else None
    return call_op(lambda v: _ihfftn_impl(v, s, ax, norm), ensure_tensor(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .framework import dtypes
        out = out.astype(dtypes.convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .framework import dtypes
        out = out.astype(dtypes.convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return call_op(lambda v: jnp.fft.fftshift(v, axes=ax), ensure_tensor(x))


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return call_op(lambda v: jnp.fft.ifftshift(v, axes=ax), ensure_tensor(x))
