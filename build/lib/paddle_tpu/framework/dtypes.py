"""Dtype registry.

Mirrors the reference's ``paddle.dtype`` surface (reference:
paddle/phi/common/data_type.h, python/paddle/framework/dtype.py) but the
canonical representation is simply ``jnp.dtype`` — XLA owns layout/packing,
so no DataType enum is needed.
"""
import jax.numpy as jnp
import numpy as np

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool, "complex64": complex64, "complex128": complex128,
}

_DEFAULT_DTYPE = [jnp.float32]


def convert_dtype(dtype):
    """Normalize any dtype spec (str | np/jnp dtype | None) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _ALIASES:
            raise ValueError(f"unknown dtype {dtype!r}")
        return np.dtype(_ALIASES[dtype])
    return np.dtype(dtype)


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = convert_dtype(d)


def get_default_dtype():
    return np.dtype(_DEFAULT_DTYPE[0])


def is_floating_dtype(d):
    return np.issubdtype(np.dtype(d), np.floating) or np.dtype(d) == np.dtype(bfloat16)


def is_integer_dtype(d):
    return np.issubdtype(np.dtype(d), np.integer)
