"""Runtime flags registry (reference: paddle/phi/core/flags.cc — the
gflags-style FLAGS_* system exposed via paddle.set_flags).

TPU-native: a plain dict of knobs, env-overridable (``FLAGS_x=...``), plus
pass-through of ``XLA_FLAGS`` entries.  No C++ needed — XLA owns the deep
runtime knobs and we forward to it.
"""
import os

_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_cudnn_deterministic": False,  # accepted for compat; no-op
    "FLAGS_use_cinn": False,             # XLA is always the compiler
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_allocator_strategy": "xla",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_stop_check_timeout": 300,
    "FLAGS_benchmark": False,
    "FLAGS_log_level": "info",
}


def _coerce(cur, val):
    if isinstance(cur, bool):
        return str(val).lower() in ("1", "true", "yes", "on") \
            if not isinstance(val, bool) else val
    if isinstance(cur, int) and not isinstance(cur, bool):
        return int(val)
    if isinstance(cur, float):
        return float(val)
    return val


def _load_env():
    for k in list(_FLAGS):
        if k in os.environ:
            _FLAGS[k] = _coerce(_FLAGS[k], os.environ[k])


_load_env()


def set_flags(flags):
    for k, v in flags.items():
        cur = _FLAGS.get(k)
        _FLAGS[k] = _coerce(cur, v) if cur is not None else v
        if k == "FLAGS_check_nan_inf" and _FLAGS[k]:
            import jax
            jax.config.update("jax_debug_nans", True)
        elif k == "FLAGS_check_nan_inf":
            import jax
            jax.config.update("jax_debug_nans", False)


def get_flags(flags=None):
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}
