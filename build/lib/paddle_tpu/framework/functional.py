"""The dygraph↔pure-function seam.

The reference bridges eager layers to compiled programs with the
dygraph-to-static AST transpiler (reference:
python/paddle/jit/dy2static/program_translator.py).  On TPU we don't need
source transforms: JAX traces Python directly.  What we DO need is a clean
state-capture boundary — paddle Layers are mutable objects holding Parameter
tensors, while jit/grad want pure pytree functions.

``functional_call(layer, params, fn)`` temporarily rebinds every parameter's
raw array to the given pytree leaves, runs ``fn`` with the tape suspended,
and restores.  All jit/grad/pjit paths (Model.fit's compiled train step,
to_static, parallel wrappers) go through this one seam.
"""
from contextlib import contextmanager

from . import autograd as _ag

__all__ = ["capture_params", "functional_call", "swap_params"]


def capture_params(layer, include_buffers=True, trainable_only=False):
    """Return (names, tensors) for the layer's state in deterministic order."""
    named = list(layer.named_parameters())
    if trainable_only:
        named = [(n, p) for n, p in named if not p.stop_gradient]
    if include_buffers:
        named += [(f"__buf__{n}", b) for n, b in layer.named_buffers()]
    names = [n for n, _ in named]
    tensors = [t for _, t in named]
    return names, tensors


@contextmanager
def swap_params(tensors, values):
    """Rebind each tensor's raw array to the corresponding traced value."""
    originals = [t._value for t in tensors]
    for t, v in zip(tensors, values):
        t._value = v
    try:
        with _ag.suspend_tape():
            yield
    finally:
        for t, orig in zip(tensors, originals):
            t._value = orig


def functional_call(layer, fn, params_values, buffers_values=None,
                    param_tensors=None, buffer_tensors=None):
    """Run ``fn()`` with layer params (and optionally buffers) rebound.

    ``param_tensors``/``buffer_tensors`` can be precomputed (hot path) to
    avoid re-walking the module tree every step.
    """
    if param_tensors is None:
        param_tensors = [p for _, p in layer.named_parameters()]
    if buffer_tensors is None and buffers_values is not None:
        buffer_tensors = [b for _, b in layer.named_buffers()]
    tensors = list(param_tensors)
    values = list(params_values)
    if buffers_values is not None:
        tensors += list(buffer_tensors)
        values += list(buffers_values)
    with swap_params(tensors, values):
        return fn()
