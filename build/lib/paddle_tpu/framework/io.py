"""paddle.save/load-style checkpointing (reference:
python/paddle/framework/io.py).

Format: pickle of a nested structure where Tensors are materialized as a
small marker dict with numpy payload — portable, mmap-friendly, no jax
objects inside the pickle.  Sharding-aware async checkpointing for the
distributed path lives in paddle_tpu.distributed.checkpoint (orbax-style);
this module is the single-process core API.
"""
import io as _io
import os
import pickle

import numpy as np
import jax.numpy as jnp

from .core import Tensor

__all__ = ["save", "load"]

_TENSOR_KEY = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Tensor):
        return {_TENSOR_KEY: True, "data": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _pack(obj.state_dict())
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_TENSOR_KEY):
            if return_numpy:
                return obj["data"]
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj.get("stop_gradient", True),
                       name=obj.get("name"))
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    d = os.path.dirname(str(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    if hasattr(path, "read"):
        data = pickle.load(path)
    else:
        with open(path, "rb") as f:
            data = pickle.load(f)
    return _unpack(data, return_numpy)
