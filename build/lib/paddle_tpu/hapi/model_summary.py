"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
import numpy as np

from ..framework.core import Tensor
from ..framework import autograd as _ag

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            n_params = sum(int(np.prod(p.shape))
                           for p in l._parameters.values()
                           if p is not None)
            rows.append((name or type(l).__name__,
                         tuple(out.shape) if hasattr(out, "shape") else "?",
                         n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, l in net.named_sublayers(include_self=False):
        if not l._sub_layers:  # leaves only
            register(l, f"{type(l).__name__}[{name}]")

    if input is not None:
        ins = input if isinstance(input, (list, tuple)) else [input]
    else:
        sizes = input_size if isinstance(input_size, list) else [input_size]
        ins = []
        for i, s in enumerate(sizes):
            shape = tuple(2 if d is None or d == -1 else d for d in s)
            dt = (dtypes[i] if isinstance(dtypes, (list, tuple))
                  else dtypes) or "float32"
            ins.append(Tensor(np.zeros(shape, dtype=dt)))
    was_training = net.training
    net.eval()
    try:
        with _ag.no_grad():
            net(*ins)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    w = 72
    print("-" * w)
    print(f"{'Layer (type)':<36}{'Output Shape':<22}{'Param #':<12}")
    print("=" * w)
    for name, shape, n in rows:
        print(f"{name:<36}{str(shape):<22}{n:<12,}")
    print("=" * w)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * w)
    return {"total_params": total, "trainable_params": trainable}
