"""paddle.hub compat (reference: python/paddle/hapi/hub.py).

No network in scope: only ``source='local'`` entrypoints are supported.
"""
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):
    if source != "local":
        raise ValueError("only source='local' is supported (no network)")
    mod = _load_hubconf(repo_dir)
    return [k for k in dir(mod) if callable(getattr(mod, k))
            and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    if source != "local":
        raise ValueError("only source='local' is supported (no network)")
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(**kwargs)
