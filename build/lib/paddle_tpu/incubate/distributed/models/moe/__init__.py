"""MoE (reference: python/paddle/incubate/distributed/models/moe/)."""
from .moe_layer import MoELayer, ExpertLayer  # noqa: F401
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .utils import global_scatter, global_gather  # noqa: F401
