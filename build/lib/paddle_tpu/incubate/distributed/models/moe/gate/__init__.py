"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
— NaiveGate, GShardGate (top-2 + load-balance aux loss), SwitchGate
(top-1 + aux loss), each a small Layer owning the router weight).

TPU-native: gates return dense routing tensors (combine weights + dispatch
mask) built with one-hot matmuls and cumsum position assignment — the
GShard dense-dispatch formulation that XLA tiles onto the MXU — instead of
the reference's index-based scatter (prims that would force dynamic shapes
under jit).
"""
import jax
import jax.numpy as jnp

from ......framework.core import Tensor
from ...... import nn

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def _top_k_sparse_routing(logits, top_k, capacity):
    """Sparse (capacity-bucketed) GShard routing on raw jnp arrays.

    logits: (T, E) fp32. Returns ``(eidx, pos, weight, keep, aux)`` with
    eidx/pos int32 (T, K) — the chosen expert and its capacity slot for
    each of a token's K choices — weight fp32 (T, K) the renormalized
    combine weight (already zeroed for dropped assignments), and keep
    bool (T, K).  Position-in-expert is assigned by cumsum in token
    order; tokens beyond capacity are dropped.  This is the O(T*K)
    routing record that the scatter/gather dispatch consumes; the dense
    (T, E, C) tensors of :func:`_top_k_routing` are derived from it.
    """
    T, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # aux load-balance loss uses the FIRST choice only (GShard eq. (4)):
    # l_aux = E * mean(me * ce), me = mean gate prob, ce = fraction routed
    top1 = jnp.argmax(gates, axis=-1)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * E

    remaining = gates
    # per-expert fill count carried across the k choices so 2nd choices
    # take positions after 1st choices
    fill = jnp.zeros((E,), jnp.int32)
    denom = jnp.zeros((T,), jnp.float32)
    eidxs, poss, keeps, probs = [], [], [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)            # (T,)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.int32)  # (T, E)
        pos_te = jnp.cumsum(mask, axis=0) - 1 + fill[None, :]  # (T, E)
        pos = jnp.sum(pos_te * mask, axis=-1)           # (T,)
        keep = pos < capacity
        pos = jnp.clip(pos, 0, capacity - 1)
        prob = jnp.sum(gates * mask, axis=-1)           # (T,)
        eidxs.append(idx.astype(jnp.int32))
        poss.append(pos.astype(jnp.int32))
        keeps.append(keep)
        probs.append(prob)
        denom = denom + prob * keep
        fill = fill + jnp.sum(mask * keep[:, None].astype(jnp.int32),
                              axis=0)
        remaining = remaining * (1 - mask)
    denom = jnp.maximum(denom, 1e-9)
    eidx = jnp.stack(eidxs, axis=1)
    pos = jnp.stack(poss, axis=1)
    keep = jnp.stack(keeps, axis=1)
    weight = jnp.stack(probs, axis=1) / denom[:, None] \
        * keep.astype(jnp.float32)
    return eidx, pos, weight, keep, aux


def _densify_routing(eidx, pos, weight, capacity, num_expert):
    """Sparse routing record -> dense (combine (T,E,C), dispatch bool)."""
    oh_e = jax.nn.one_hot(eidx, num_expert, dtype=jnp.float32)  # (T,K,E)
    oh_c = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)     # (T,K,C)
    combine = jnp.einsum("tke,tkc,tk->tec", oh_e, oh_c, weight)
    return combine, combine > 0


def _top_k_routing(logits, top_k, capacity, jitter_key=None):
    """Dense GShard routing on raw jnp arrays.

    logits: (T, E) fp32. Returns (combine (T,E,C), dispatch bool (T,E,C),
    aux_loss scalar).  Derived from the sparse routing record so the
    dense-einsum and scatter/gather dispatch paths agree bit-for-bit on
    the routing decision.
    """
    E = logits.shape[1]
    eidx, pos, weight, _, aux = _top_k_sparse_routing(
        logits, top_k, capacity)
    combine, dispatch = _densify_routing(eidx, pos, weight, capacity, E)
    return combine, dispatch, aux


class BaseGate(nn.Layer):
    def __init__(self, d_model, num_expert, world_size=1, top_k=2,
                 capacity_factor=None):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert            # experts per EP rank
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.top_k = top_k
        self.capacity_factor = capacity_factor or float(top_k)
        self.weight = self.create_parameter(
            shape=[d_model, self.tot_expert], is_bias=False)
        self.loss = None  # aux loss of the last forward (reference: get_loss)

    def capacity(self, num_tokens):
        cap = int(self.capacity_factor * num_tokens / self.tot_expert)
        return max(cap, 4)

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def route(self, logits, num_tokens):
        """raw (T, E) logits -> (combine, dispatch, aux).  THE policy
        seam: subclasses override this; MoELayer calls it inside its
        traced forward."""
        return _top_k_routing(logits, self.top_k,
                              self.capacity(num_tokens))

    def route_sparse(self, logits, num_tokens):
        """raw (T, E) logits -> (eidx, pos, weight, keep, aux, capacity)
        — the O(T*K) routing record consumed by MoELayer's scatter/gather
        dispatch (reference global_scatter/global_gather semantics).
        Subclasses with a custom dense ``route`` policy need not override
        this; MoELayer falls back to the dense path for them."""
        cap = self.capacity(num_tokens)
        eidx, pos, weight, keep, aux = _top_k_sparse_routing(
            logits, self.top_k, cap)
        return eidx, pos, weight, keep, aux, cap

    def routing(self, x_value):
        """Standalone raw (T, M) -> routing (eager use)."""
        return self.route(x_value @ self.weight._value, x_value.shape[0])

    def forward(self, x):
        raise NotImplementedError


class NaiveGate(BaseGate):
    """top-k routing, no auxiliary loss recorded."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert, world_size, top_k=topk)

    def route(self, logits, num_tokens):
        c, d, _ = super().route(logits, num_tokens)
        return c, d, jnp.zeros((), jnp.float32)

    def route_sparse(self, logits, num_tokens):
        eidx, pos, weight, keep, _, cap = super().route_sparse(
            logits, num_tokens)
        return eidx, pos, weight, keep, jnp.zeros((), jnp.float32), cap


class GShardGate(BaseGate):
    """top-2 with load-balance aux loss and capacity (GShard §3.2)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None, gate_bias=True):
        cap = capacity[0] * topk if isinstance(capacity, (tuple, list)) \
            else capacity
        super().__init__(d_model, num_expert, world_size, top_k=topk,
                         capacity_factor=cap)


class SwitchGate(BaseGate):
    """top-1 Switch-Transformer routing with aux loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, top_k=1,
                         capacity_factor=capacity[0]
                         if isinstance(capacity, (tuple, list))
                         else capacity)
        self.switch_eps = switch_eps

    def _jitter(self, logits):
        # Switch jitters logits multiplicatively during training for
        # exploration (reference: switch_gate.py uniform(1-eps, 1+eps));
        # folded in via the framework RNG so routing stays reproducible
        if self.training and self.switch_eps:
            import jax as _jax
            from ......framework.random import next_key, in_rng_scope
            if in_rng_scope():
                key = next_key()
                noise = _jax.random.uniform(
                    key, logits.shape, jnp.float32,
                    1.0 - self.switch_eps, 1.0 + self.switch_eps)
                logits = logits * noise
        return logits

    def route(self, logits, num_tokens):
        return _top_k_routing(self._jitter(logits), 1,
                              self.capacity(num_tokens))

    def route_sparse(self, logits, num_tokens):
        cap = self.capacity(num_tokens)
        eidx, pos, weight, keep, aux = _top_k_sparse_routing(
            self._jitter(logits), 1, cap)
        return eidx, pos, weight, keep, aux, cap
