"""MoE-aware global-norm grad clip (reference:
python/paddle/incubate/distributed/models/moe/grad_clip.py —
ClipGradForMOEByGlobalNorm sums expert-param norms across the MoE group so
each expert's grad counts once globally).

TPU-native: parameters (incl. expert-stacked ones) are logically GLOBAL
arrays under GSPMD — the compiled global-norm reduction over a sharded
(E, ...) weight already produces the cross-rank sum the reference builds by
hand, so this subclass only tags the moe params for bookkeeping."""
from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group


ClipGradForMoEByGlobalNorm = ClipGradForMOEByGlobalNorm  # alias
