"""MoE comm utilities (reference: paddle.distributed.utils.global_scatter /
global_gather — paddle/fluid/operators/collective/global_scatter_op.cu:
all-to-all exchange of per-(rank, expert) token counts then token rows).

TPU-native: inside a shard_map over the expert axis these lower to
``lax.all_to_all``; the dense-dispatch MoELayer does not need them (XLA
inserts the exchange from shardings), they exist for API parity and for
custom token-level MoE schemes."""
import jax.numpy as jnp
from jax import lax

from .....framework.core import Tensor
from .....framework.autograd import call_op

__all__ = ["global_scatter", "global_gather"]


def _exchange(x, axis, split_axis=0):
    def f(v):
        try:
            lax.axis_index(axis)
        except Exception:
            return v  # eager / world of 1: identity
        return lax.all_to_all(v, axis, split_axis=split_axis,
                              concat_axis=split_axis, tiled=True)
    return call_op(f, x) if isinstance(x, Tensor) else f(jnp.asarray(x))


def global_scatter(x, local_count=None, global_count=None, group=None,
                   use_calc_stream=True, axis="model"):
    """Dispatch rows to the expert ranks.  With the dense equal-capacity
    layout (E*C rows per rank, E = experts * world) this is one tiled
    all-to-all on dim 0; counts args are accepted for API parity."""
    return _exchange(x, axis)


def global_gather(x, local_count=None, global_count=None, group=None,
                  use_calc_stream=True, axis="model"):
    """Inverse of global_scatter (all-to-all is an involution on the
    equal-split layout)."""
    return _exchange(x, axis)
