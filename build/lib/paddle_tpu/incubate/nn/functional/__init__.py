"""Incubate functionals (reference: python/paddle/incubate/nn/functional/
— fused_multi_head_attention, flash_attention wrapper over the cutlass
submodule).

TPU-native: flash attention dispatches to the Pallas kernel (M3) when on
TPU with compatible shapes, falling back to the XLA softmax composition
(which XLA fuses well on its own).
"""
import math

import jax
import jax.numpy as jnp

from ....framework.core import Tensor
from ....framework.autograd import call_op
from ....tensor._helpers import ensure_tensor

__all__ = ["flash_attention", "scaled_dot_product_attention",
           "fused_multi_head_attention", "flash_attn_unpadded"]


def _sdpa(q, k, v, mask=None, dropout=0.0, causal=False, scale=None):
    """q,k,v: (B, S, H, D) paddle flash-attention layout."""
    d = q.shape[-1]
    s = scale or (1.0 / math.sqrt(d))
    # -> (B,H,S,D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * s
    if causal:
        S, T = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((S, T), bool))
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention layout: (B, S, H, D)."""
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    use_pallas = _pallas_ok(q)
    if use_pallas:
        from ....ops.pallas.flash_attention import flash_attention_fwd
        out = call_op(lambda a, b, c: flash_attention_fwd(
            a, b, c, causal=causal), q, k, v)
    else:
        out = call_op(lambda a, b, c: _sdpa(a, b, c, causal=causal), q, k, v)
    if return_softmax:
        return out, None
    return out, None


def _pallas_ok(q):
    try:
        import jax
        dev = jax.devices()[0].platform
        if dev == "cpu":
            return False
        B, S, H, D = q.shape
        return S % 128 == 0 and D in (64, 128, 256)
    except Exception:
        return False


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if attn_mask is not None:
        m = ensure_tensor(attn_mask)
        return call_op(lambda a, b, c, mm: _sdpa(a, b, c, mask=mm,
                                                 causal=is_causal),
                       q, k, v, m)
    return call_op(lambda a, b, c: _sdpa(a, b, c, causal=is_causal), q, k, v)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    raise NotImplementedError(
        "varlen flash attention lands with the Pallas kernel suite (M3)")


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args, **kw):
    raise NotImplementedError(
        "use paddle_tpu.nn.MultiHeadAttention; XLA fuses the composed ops")
