"""Bounded byte-buffer blocking queue over the native C++ core
(paddle_tpu/csrc/blocking_queue.cc; reference: the reader blocking queue
in paddle/fluid/operators/reader/ fed by the Python DataLoader).  Python
``queue.Queue`` fallback keeps semantics identical without the toolchain.
"""
import ctypes
import queue as _pyqueue

from ..framework import native

__all__ = ["BlockingQueue"]


class BlockingQueue:
    """push/pop bytes with backpressure.  close() wakes waiters; pending
    items stay poppable (drain-then-end), then pop returns None."""

    def __init__(self, capacity):
        self._lib = native.get_lib()
        self._closed = False
        if self._lib is not None:
            self._h = self._lib.pt_queue_create(int(capacity))
        else:
            self._q = _pyqueue.Queue(maxsize=int(capacity))

    def push(self, data: bytes, timeout=None):
        """True if enqueued; False on timeout or closed queue."""
        tmo = -1 if timeout is None else int(timeout * 1000)
        if self._lib is not None:
            buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) \
                if data else None
            return self._lib.pt_queue_push(self._h, buf, len(data), tmo) == 0
        # Poll in short slices so close() can wake a blocked producer
        # (the native path wakes waiters via its condition variable).
        remaining = timeout
        while True:
            if self._closed:
                return False
            try:
                self._q.put(data, timeout=0.05 if remaining is None
                            else min(remaining, 0.05))
                return True
            except _pyqueue.Full:
                if remaining is not None:
                    remaining -= 0.05
                    if remaining <= 0:
                        return False

    def pop(self, timeout=None):
        """bytes, or None when the queue is closed and drained.
        Raises TimeoutError on timeout."""
        tmo = -1 if timeout is None else int(timeout * 1000)
        if self._lib is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.pt_queue_pop(self._h, tmo, ctypes.byref(out))
            if n == -1:
                raise TimeoutError("BlockingQueue.pop timed out")
            if n == -2:
                return None
            return native.take_buffer(self._lib, out, n)
        while True:
            try:
                return self._q.get(
                    timeout=0.05 if self._closed or timeout is None
                    else min(timeout, 0.05))
            except _pyqueue.Empty:
                if self._closed and self._q.empty():
                    return None
                if timeout is not None:
                    timeout -= 0.05
                    if timeout <= 0:
                        raise TimeoutError("BlockingQueue.pop timed out")

    def size(self):
        if self._lib is not None:
            return self._lib.pt_queue_size(self._h)
        return self._q.qsize()

    def close(self):
        self._closed = True
        if self._lib is not None and self._h:
            self._lib.pt_queue_close(self._h)

    def destroy(self):
        if self._lib is not None and getattr(self, "_h", 0):
            self._lib.pt_queue_destroy(self._h)
            self._h = 0

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
