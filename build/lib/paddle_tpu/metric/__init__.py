"""Metrics (reference: python/paddle/metric/metrics.py) — the
update/accumulate/reset protocol consumed by hapi Model.fit."""
import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        accs = []
        num = c.reshape(-1, c.shape[-1]).shape[0]
        for k in self.topk:
            right = c[..., :k].sum()
            self.total[self.topk.index(k)] += right
            self.count[self.topk.index(k)] += num
            accs.append(right / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    pred = _np(input)
    lab = _np(label).reshape(-1)
    idx = np.argsort(-pred, axis=-1)[:, :k]
    c = (idx == lab[:, None]).any(axis=1).mean()
    return Tensor(jnp.asarray(np.float32(c)))
