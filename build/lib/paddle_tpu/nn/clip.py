"""Gradient clipping (reference: python/paddle/nn/clip.py).

Clip objects transform a list of (param, grad) pairs; the optimizer applies
them before the update, exactly like the reference's ``GradientClipBase``
protocol.  The distributed variants (hybrid-parallel global-norm across mesh
axes) subclass ClipGradByGlobalNorm in distributed/fleet.
"""
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, None if g is None else
                 Tensor(jnp.clip(g._value, self.min, self.max))
                 if isinstance(g, Tensor) else jnp.clip(g, self.min, self.max))
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return g * scale

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            elif isinstance(g, Tensor):
                out.append((p, Tensor(self._clip_one(g._value))))
            else:
                out.append((p, self._clip_one(g)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm_sq(self, grads):
        """Sum of squares over local grads; distributed subclasses add the
        cross-axis psum here."""
        return sum(jnp.sum(jnp.square(
            g.astype(jnp.float32))) for g in grads)

    def __call__(self, params_grads):
        raw = [(p, g._value if isinstance(g, Tensor) else g)
               for p, g in params_grads]
        grads = [g for _, g in raw if g is not None]
        if not grads:
            return params_grads
        gn = jnp.sqrt(self._global_norm_sq(grads))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for (p, g_orig), (_, g) in zip(params_grads, raw):
            if g is None:
                out.append((p, g_orig))
            else:
                clipped = (g.astype(jnp.float32) * scale).astype(g.dtype)
                out.append((p, Tensor(clipped)
                            if isinstance(g_orig, Tensor) else clipped))
        return out
