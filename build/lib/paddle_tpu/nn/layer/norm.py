"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
import jax.numpy as jnp

from ...framework.core import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else \
            self.create_parameter((num_features,), attr=weight_attr,
                                  default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (acts like BatchNorm2D w/ act option)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 data_layout="NCHW", **kw):
        super().__init__(num_channels, momentum, epsilon,
                         data_format=data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under pjit/GSPMD, batch stats computed inside the sharded program are
    already global (XLA inserts the collective for the mean/var reductions
    when the batch axis is sharded) — so the single-device implementation is
    reused; the reference needed an explicit NCCL allreduce
    (paddle/fluid/operators/sync_batch_norm_op.cu).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else \
            self.create_parameter(self._normalized_shape, attr=weight_attr,
                                  default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class RMSNorm(Layer):
    """LLaMA-family RMSNorm; maps to the fused Pallas kernel on TPU."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else \
            self.create_parameter((num_features,), attr=weight_attr,
                                  default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               epsilon=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else \
            self.create_parameter((num_channels,), attr=weight_attr,
                                  default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            (h,), default_initializer=Normal(0, 1))
        self.weight_v = self.create_parameter(
            (w,), default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...tensor.manipulation import reshape, moveaxis
        w = weight
        if self._dim != 0:
            w = moveaxis(w, self._dim, 0)
        h = w.shape[0]
        wm = reshape(w, [h, -1])
        u, v = self.weight_u._value, self.weight_v._value
        import jax.numpy as jnp
        wv = wm._value
        for _ in range(self._power_iters):
            v = wv.T @ u
            v = v / (jnp.linalg.norm(v) + self._epsilon)
            u = wv @ v
            u = u / (jnp.linalg.norm(u) + self._epsilon)
        self.weight_u._value = u
        self.weight_v._value = v
        sigma = (u @ wv @ v)
        from ...framework.autograd import call_op
        out = call_op(lambda W: W / sigma, weight)
        return out
