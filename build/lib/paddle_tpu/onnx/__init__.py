"""paddle.onnx (reference: python/paddle/onnx/export.py — a thin delegate
to the external ``paddle2onnx`` package).

TPU-native: the deployment interchange format of this framework is
serialized StableHLO (``jit.save`` / ``paddle_tpu.inference``), which XLA
consumers load directly.  ONNX export is gated exactly like the reference
gates on paddle2onnx: if an ``onnx``-capable converter is importable we
would delegate; in this environment none is bundled, so ``export`` writes
the StableHLO artifact next to the requested path and raises a clear error
only if the caller insists on a true ``.onnx`` file.
"""
import os
import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9,
           enable_onnx_checker=True, **configs):
    """paddle.onnx.export-shaped entry.

    Without an ONNX converter on the box, exports the model as a StableHLO
    artifact at ``path`` (plus ``.pdmodel``/``.pdiparams``) and warns; the
    file layout matches jit.save so paddle_tpu.inference can load it.
    """
    # no ONNX converter is bundled (reference delegates to the external
    # paddle2onnx); export the StableHLO artifact in every case so the
    # call always yields a loadable deployment file
    from .. import jit as _jit
    base = path[:-5] if path.endswith(".onnx") else path
    warnings.warn(
        "no ONNX converter available — exporting StableHLO artifact "
        f"({base}.pdmodel/.pdiparams) instead; load it with "
        "paddle_tpu.inference.create_predictor", stacklevel=2)
    _jit.save(layer, base, input_spec=input_spec)
    return base + ".pdmodel"
