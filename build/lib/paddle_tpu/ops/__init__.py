from . import pallas  # noqa: F401
from .ring_attention import ring_flash_attention, ulysses_attention  # noqa: F401
