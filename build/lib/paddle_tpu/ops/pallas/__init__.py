"""Pallas TPU kernels — the native-kernel layer answering the reference's
CUDA kernel library (paddle/phi/kernels/gpu/, fusion/).

Kernels: flash attention (+ring variant for context parallel), fused
layernorm/rmsnorm, fused optimizer updates.  Each has an XLA-composed
fallback for CPU tests; dispatch happens at the functional layer.
"""
