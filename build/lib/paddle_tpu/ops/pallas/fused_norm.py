"""Fused LayerNorm / RMSNorm Pallas kernels (TPU).

Reference analogue: paddle/phi/kernels/fusion/gpu/fused_layernorm* (and
the rms_norm fused op).  One VMEM-resident pass computes the row
statistics and the normalized, scaled output — fp32 statistics regardless
of the input dtype (bf16-safe), one HBM read + one write per element
instead of the unfused stat/normalize/scale chain.

Custom VJP: the backward recomputes the cheap statistics from the saved
normalized activations, so no mean/rstd tensors are materialized between
fwd and bwd (the memory-bound regime on TPU is HBM traffic, not FLOPs).

Exposes ``fused_layer_norm`` / ``fused_rms_norm`` over (..., H) arrays;
falls back to plain jnp on non-TPU backends (CPU testability — same
numerics, looser perf).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_layer_norm", "fused_rms_norm"]

_BLOCK_ROWS = 256


def _on_tpu():
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)           # (rows, H)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) \
        + b_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, g_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * rstd * g_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rows_call(kernel, x2, weights, eps):
    """Grid over row blocks; weights broadcast to every block."""
    R, H = x2.shape
    block = min(_BLOCK_ROWS, R)
    while R % block:
        block //= 2
    block = max(block, 1)
    grid = (R // block,)
    in_specs = [pl.BlockSpec((block, H), lambda i: (i, 0))] + \
        [pl.BlockSpec((H,), lambda i: (0,)) for _ in weights]
    return pl.pallas_call(
        functools.partial(kernel, eps=eps),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x2.dtype),
    )(x2, *weights)


# ---------------------------------------------------------------------------
# layer norm
# ---------------------------------------------------------------------------

def _ln_ref(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(
        x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last dim with affine params, fused on TPU."""
    if not _on_tpu():
        return _ln_ref(x, gamma, beta, eps)
    shape = x.shape
    y = _rows_call(_ln_kernel, x.reshape(-1, shape[-1]), (gamma, beta),
                   eps)
    return y.reshape(shape)


def _ln_fwd(x, gamma, beta, eps):
    y = fused_layer_norm(x, gamma, beta, eps)
    return y, (x, gamma, beta)


def _ln_bwd(eps, res, dy):
    x, gamma, beta = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    gf = gamma.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    H = x.shape[-1]
    dxhat = dyf * gf
    dx = (dxhat - jnp.mean(dxhat, -1, keepdims=True)
          - xhat * jnp.mean(dxhat * xhat, -1, keepdims=True)) * rstd
    red = tuple(range(x.ndim - 1))
    dgamma = jnp.sum(dyf * xhat, axis=red).astype(gamma.dtype)
    dbeta = jnp.sum(dyf, axis=red).astype(beta.dtype)
    return dx.astype(x.dtype), dgamma, dbeta


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# rms norm
# ---------------------------------------------------------------------------

def _rms_ref(x, g, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)
            * g.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x, gamma, eps=1e-6):
    """RMSNorm over the last dim (LLaMA-style), fused on TPU."""
    if not _on_tpu():
        return _rms_ref(x, gamma, eps)
    shape = x.shape
    y = _rows_call(_rms_kernel, x.reshape(-1, shape[-1]), (gamma,), eps)
    return y.reshape(shape)


def _rms_fwd(x, gamma, eps):
    return fused_rms_norm(x, gamma, eps), (x, gamma)


def _rms_bwd(eps, res, dy):
    x, gamma = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    gf = gamma.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = xf * rstd
    dxhat = dyf * gf
    dx = (dxhat - xhat * jnp.mean(dxhat * xhat, -1, keepdims=True)) * rstd
    red = tuple(range(x.ndim - 1))
    dgamma = jnp.sum(dyf * xhat, axis=red).astype(gamma.dtype)
    return dx.astype(x.dtype), dgamma


fused_rms_norm.defvjp(_rms_fwd, _rms_bwd)
