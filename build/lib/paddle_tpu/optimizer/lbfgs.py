"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py).

TPU-native notes: L-BFGS is inherently sequential and host-driven (the line
search re-evaluates the closure a data-dependent number of times), so the
driver loop lives in Python while every closure evaluation is itself an
eager/jitted device computation.  History vectors are kept as flat jnp
arrays on device; the two-loop recursion is a handful of dots/axpys that
XLA fuses per call.
"""
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import no_grad
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2)."""
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 ** 2 - g1 * g2
    if d2_square >= 0:
        d2 = d2_square ** 0.5
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


class LBFGS(Optimizer):
    """Limited-memory BFGS with optional strong-Wolfe line search.

    ``step(closure)`` — closure clears grads, computes loss, runs backward,
    returns the loss Tensor.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        self.max_iter = max_iter
        self.max_eval = max_eval
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only 'strong_wolfe' line search is supported")
        self.line_search_fn = line_search_fn
        self._lbfgs_state = {}

    # -- flat param/grad helpers -------------------------------------------
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("LBFGS requires an explicit parameters list")
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _gather_flat_grad(self):
        views = []
        for p in self._params():
            g = p.grad
            if g is None:
                views.append(jnp.zeros(p._value.size, p._value.dtype))
            else:
                gv = g._value if isinstance(g, Tensor) else g
                views.append(gv.reshape(-1))
        return jnp.concatenate(views)

    def _add_grad(self, step_size, update):
        offset = 0
        with no_grad():
            for p in self._params():
                numel = p._value.size
                chunk = update[offset:offset + numel].reshape(p._value.shape)
                p._value = p._value + step_size * chunk.astype(p._value.dtype)
                offset += numel

    def _clone_param(self):
        return [p._value for p in self._params()]

    def _set_param(self, params_data):
        for p, pdata in zip(self._params(), params_data):
            p._value = pdata

    def _directional_evaluate(self, closure, x, t, d):
        self._add_grad(t, d)
        loss = float(closure()._value)
        flat_grad = self._gather_flat_grad()
        self._set_param(x)
        return loss, flat_grad

    # -- strong Wolfe line search ------------------------------------------
    def _strong_wolfe(self, closure, x, t, d, f, g, gtd,
                      c1=1e-4, c2=0.9, tolerance_change=1e-9, max_ls=25):
        d_norm = float(jnp.abs(d).max())
        f_new, g_new = self._directional_evaluate(closure, x, t, d)
        ls_func_evals = 1
        gtd_new = float(jnp.dot(g_new, d))

        t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
        done = False
        ls_iter = 0
        bracket = bracket_f = bracket_g = bracket_gtd = None
        while ls_iter < max_ls:
            if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new]
                bracket_gtd = [gtd_prev, gtd_new]
                break
            if abs(gtd_new) <= -c2 * gtd:
                bracket = [t, t]
                bracket_f = [f_new, f_new]
                bracket_g = [g_new, g_new]
                done = True
                break
            if gtd_new >= 0:
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new]
                bracket_gtd = [gtd_prev, gtd_new]
                break
            min_step = t + 0.01 * (t - t_prev)
            max_step = t * 10
            tmp = t
            t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new, gtd_new,
                                   bounds=(min_step, max_step))
            t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new, gtd_new
            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            ls_func_evals += 1
            gtd_new = float(jnp.dot(g_new, d))
            ls_iter += 1
        if ls_iter == max_ls:
            bracket = [0.0, t]
            bracket_f = [f, f_new]
            bracket_g = [g, g_new]
            bracket_gtd = [gtd, gtd_new]

        # zoom phase
        insuf_progress = False
        low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] else (1, 0)
        while not done and ls_iter < max_ls:
            if abs(bracket[1] - bracket[0]) * d_norm < tolerance_change:
                break
            t = _cubic_interpolate(bracket[0], bracket_f[0], bracket_gtd[0],
                                   bracket[1], bracket_f[1], bracket_gtd[1])
            eps = 0.1 * (max(bracket) - min(bracket))
            if min(max(bracket) - t, t - min(bracket)) < eps:
                if insuf_progress or t >= max(bracket) or t <= min(bracket):
                    if abs(t - max(bracket)) < abs(t - min(bracket)):
                        t = max(bracket) - eps
                    else:
                        t = min(bracket) + eps
                    insuf_progress = False
                else:
                    insuf_progress = True
            else:
                insuf_progress = False
            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            ls_func_evals += 1
            gtd_new = float(jnp.dot(g_new, d))
            ls_iter += 1
            if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
                bracket[high_pos] = t
                bracket_f[high_pos] = f_new
                bracket_g[high_pos] = g_new
                bracket_gtd[high_pos] = gtd_new
                low_pos, high_pos = ((0, 1) if bracket_f[0] <= bracket_f[1]
                                     else (1, 0))
            else:
                if abs(gtd_new) <= -c2 * gtd:
                    done = True
                elif gtd_new * (bracket[high_pos] - bracket[low_pos]) >= 0:
                    bracket[high_pos] = bracket[low_pos]
                    bracket_f[high_pos] = bracket_f[low_pos]
                    bracket_g[high_pos] = bracket_g[low_pos]
                    bracket_gtd[high_pos] = bracket_gtd[low_pos]
                bracket[low_pos] = t
                bracket_f[low_pos] = f_new
                bracket_g[low_pos] = g_new
                bracket_gtd[low_pos] = gtd_new

        t = bracket[low_pos]
        f_new = bracket_f[low_pos]
        g_new = bracket_g[low_pos]
        return f_new, g_new, t, ls_func_evals

    # -- main ---------------------------------------------------------------
    def step(self, closure):
        state = self._lbfgs_state
        state.setdefault("func_evals", 0)
        state.setdefault("n_iter", 0)

        orig_loss = closure()
        loss = float(orig_loss._value)
        current_evals = 1
        state["func_evals"] += 1

        flat_grad = self._gather_flat_grad()
        if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
            return orig_loss

        d = state.get("d")
        t = state.get("t")
        old_dirs = state.get("old_dirs", [])
        old_stps = state.get("old_stps", [])
        ro = state.get("ro", [])
        H_diag = state.get("H_diag")
        prev_flat_grad = state.get("prev_flat_grad")
        prev_loss = state.get("prev_loss")

        n_iter = 0
        lr = self.get_lr()
        while n_iter < self.max_iter:
            n_iter += 1
            state["n_iter"] += 1
            if state["n_iter"] == 1:
                d = -flat_grad
                old_dirs, old_stps, ro = [], [], []
                H_diag = 1.0
            else:
                y = flat_grad - prev_flat_grad
                s = d * t
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(old_dirs) == self.history_size:
                        old_dirs.pop(0)
                        old_stps.pop(0)
                        ro.pop(0)
                    old_dirs.append(y)
                    old_stps.append(s)
                    ro.append(1.0 / ys)
                    H_diag = ys / float(jnp.dot(y, y))
                num_old = len(old_dirs)
                al = [None] * num_old
                q = -flat_grad
                for i in range(num_old - 1, -1, -1):
                    al[i] = float(jnp.dot(old_stps[i], q)) * ro[i]
                    q = q - al[i] * old_dirs[i]
                d = q * H_diag
                for i in range(num_old):
                    be_i = float(jnp.dot(old_dirs[i], d)) * ro[i]
                    d = d + old_stps[i] * (al[i] - be_i)
            prev_flat_grad = flat_grad
            prev_loss = loss

            if state["n_iter"] == 1:
                t = min(1.0, 1.0 / float(jnp.abs(flat_grad).sum())) * lr
            else:
                t = lr

            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self.tolerance_change:
                break

            ls_func_evals = 0
            if self.line_search_fn == "strong_wolfe":
                x_init = self._clone_param()
                loss, flat_grad, t, ls_func_evals = self._strong_wolfe(
                    closure, x_init, t, d, loss, flat_grad, gtd)
                self._add_grad(t, d)
            else:
                self._add_grad(t, d)
                if n_iter != self.max_iter:
                    loss = float(closure()._value)
                    flat_grad = self._gather_flat_grad()
                    ls_func_evals = 1
            current_evals += ls_func_evals
            state["func_evals"] += ls_func_evals

            if n_iter == self.max_iter or current_evals >= self.max_eval:
                break
            if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
                break
            if float(jnp.abs(d * t).max()) <= self.tolerance_change:
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        state.update(dict(d=d, t=t, old_dirs=old_dirs, old_stps=old_stps,
                          ro=ro, H_diag=H_diag, prev_flat_grad=prev_flat_grad,
                          prev_loss=prev_loss))
        return orig_loss

    def state_dict(self):
        return {"lbfgs_state": self._lbfgs_state}

    def set_state_dict(self, state_dict):
        self._lbfgs_state = state_dict.get("lbfgs_state", {})
