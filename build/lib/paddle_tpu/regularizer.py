"""paddle.regularizer (reference: python/paddle/regularizer.py) —
L1Decay/L2Decay weight-decay policies consumed by the optimizers'
``weight_decay`` argument."""
from .optimizer.optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
