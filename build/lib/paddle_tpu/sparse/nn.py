"""paddle.sparse.nn — layers over sparse COO tensors (reference:
python/paddle/sparse/nn/layer/{conv,norm,activation,pooling}.py, kernels
paddle/phi/kernels/sparse/gpu/conv_kernel.cu — gather/GEMM/scatter sparse
convolution).

TPU-native design: the reference's gather-GEMM-scatter sparse conv exists
because GPU dense conv wastes FLOPs on empty space.  On TPU the MXU *is*
the dense conv engine, so the idiomatic implementation is: densify →
``lax.conv_general_dilated`` (NDHWC) → gather values at the (static per
call) output coordinate set.  Submanifold conv's output sites are by
definition the input sites, so its coordinate set is statically known;
regular sparse conv computes its output sites host-side from the concrete
input coordinates (eager mode), mirroring the reference's rulebook build
on the host.  BatchNorm/activation/pooling act on the values array.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import call_op
from ..nn.layer.layers import Layer
from . import SparseCooTensor, _unary
from . import relu as _relu_fn, relu6 as _relu6_fn, leaky_relu as _lrelu_fn
from . import softmax as _softmax_fn

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv3D", "SubmConv3D",
           "Conv2D", "SubmConv2D", "BatchNorm", "SyncBatchNorm", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return _relu_fn(x)


class ReLU6(Layer):
    def forward(self, x):
        return _relu6_fn(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return _lrelu_fn(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return _softmax_fn(x, self.axis)


def _to_list(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


class _SparseConvNd(Layer):
    """Shared machinery for (Subm)Conv2D/3D over NDHWC/NHWC COO tensors."""

    def __init__(self, in_channels, out_channels, kernel_size, ndim,
                 stride=1, padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse conv supports groups=1")
        self._ndim = ndim
        self._subm = subm
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _to_list(kernel_size, ndim)
        self._stride = _to_list(stride, ndim)
        self._padding = _to_list(padding, ndim)
        self._dilation = _to_list(dilation, ndim)
        # reference kernel layout: [*spatial, in, out]
        fan_in = int(np.prod(self._kernel_size)) * in_channels
        from ..nn.initializer import Uniform
        k = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            self._kernel_size + [in_channels, out_channels], attr=weight_attr,
            default_initializer=Uniform(-k, k))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=Uniform(-k, k))
        else:
            self.bias = None

    def _out_spatial(self, in_spatial):
        out = []
        for i, s in enumerate(in_spatial):
            k_eff = (self._kernel_size[i] - 1) * self._dilation[i] + 1
            out.append((s + 2 * self._padding[i] - k_eff)
                       // self._stride[i] + 1)
        return out

    def _out_coords(self, x):
        """Active output sites.  Subm: identical to input.  Regular: host
        computation over the concrete input coordinates (eager only),
        mirroring the reference's host-side rulebook."""
        idx = np.asarray(x._indices)        # [1+ndim, nnz] (batch + spatial)
        if self._subm:
            return x._indices
        in_spatial = x._shape[1:1 + self._ndim]
        out_spatial = self._out_spatial(in_spatial)
        coords = set()
        nnz = idx.shape[1]
        offsets = np.stack(np.meshgrid(
            *[np.arange(k) for k in self._kernel_size],
            indexing="ij")).reshape(self._ndim, -1)  # [ndim, prod(k)]
        for e in range(nnz):
            b = idx[0, e]
            pos = idx[1:1 + self._ndim, e]
            for o in range(offsets.shape[1]):
                num = (pos + np.asarray(self._padding)
                       - offsets[:, o] * np.asarray(self._dilation))
                if np.any(num % np.asarray(self._stride)):
                    continue
                oc = num // np.asarray(self._stride)
                if np.all(oc >= 0) and np.all(oc < np.asarray(out_spatial)):
                    coords.add((int(b),) + tuple(int(c) for c in oc))
        coords = sorted(coords)
        if not coords:
            coords = [(0,) * (1 + self._ndim)]
        return jnp.asarray(np.asarray(coords, np.int32).T)

    def forward(self, x):
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse conv expects a SparseCooTensor")
        ndim = self._ndim
        in_spatial = x._shape[1:1 + ndim]
        out_spatial = (in_spatial if self._subm
                       else self._out_spatial(in_spatial))
        out_coords = self._out_coords(x)
        dense = x.to_dense()               # [N, *spatial, C]
        stride = self._stride
        padding = self._padding
        dilation = self._dilation
        if self._subm:
            # submanifold: stride 1, 'same' (possibly asymmetric) padding so
            # the conv output grid matches the input grid exactly — even
            # kernels need (lo, hi) with lo+hi == (k-1)*dilation
            stride = [1] * ndim
            pad_cfg = []
            for i in range(ndim):
                total = (self._kernel_size[i] - 1) * self._dilation[i]
                lo = total // 2
                pad_cfg.append((lo, total - lo))
        else:
            pad_cfg = [(p, p) for p in padding]
        dn_spec = ("NDHWC", "DHWIO", "NDHWC") if ndim == 3 else \
                  ("NHWC", "HWIO", "NHWC")
        gather_idx = tuple(out_coords[i] for i in range(1 + ndim))

        def impl(dv, wv):
            out = jax.lax.conv_general_dilated(
                dv, wv, window_strides=stride, padding=pad_cfg,
                rhs_dilation=dilation,
                dimension_numbers=jax.lax.conv_dimension_numbers(
                    dv.shape, wv.shape, dn_spec))
            return out[gather_idx]          # [nnz_out, C_out]
        vals = call_op(impl, dense, self.weight)
        if self.bias is not None:
            vals = call_op(lambda v, b: v + b, vals, self.bias)
        out_shape = (x._shape[0],) + tuple(out_spatial) + \
            (self._out_channels,)
        return SparseCooTensor(out_coords, vals, out_shape, coalesced=False)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class BatchNorm(Layer):
    """BatchNorm over the values array: nnz acts as the batch dimension,
    stats are per-channel (reference:
    python/paddle/sparse/nn/layer/norm.py)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._use_global_stats = use_global_stats
        from ..nn.initializer import Constant
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        vals = x.values()
        use_stats = (self._use_global_stats if self._use_global_stats
                     is not None else not self.training)
        eps = self._epsilon
        if use_stats:
            mean_v, var_v = self._mean._value, self._variance._value

            def impl(v, w, b):
                return (v - mean_v) * jax.lax.rsqrt(var_v + eps) * w + b
        else:
            # batch statistics must be computed INSIDE the taped op so the
            # vjp differentiates through mean/var (d mean/d v etc.)
            def impl(v, w, b):
                mean_b = jnp.mean(v, axis=0)
                var_b = jnp.var(v, axis=0)
                return (v - mean_b) * jax.lax.rsqrt(var_b + eps) * w + b
            v = vals._value
            m = self._momentum
            self._mean._value = (m * self._mean._value
                                 + (1 - m) * jnp.mean(v, axis=0))
            self._variance._value = (m * self._variance._value
                                     + (1 - m) * jnp.var(v, axis=0))
        new_vals = call_op(impl, vals, self.weight, self.bias)
        return SparseCooTensor(x._indices, new_vals, x._shape, x._coalesced)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BN; in SPMD execution XLA computes global stats when
    the values axis is sharded — kept as an alias with the reference's name
    (reference: python/paddle/sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer, cls):
            new = cls(int(layer.weight.shape[0]), layer._momentum,
                      layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class MaxPool3D(Layer):
    """Max pooling over a sparse NDHWC tensor (dense-backed window reduce;
    output sites = pooled input sites, computed host-side)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._kernel = _to_list(kernel_size, 3)
        self._stride = _to_list(stride if stride is not None else kernel_size,
                                3)
        self._padding = _to_list(padding, 3)

    def forward(self, x):
        in_spatial = x._shape[1:4]
        out_spatial = [(in_spatial[i] + 2 * self._padding[i]
                        - self._kernel[i]) // self._stride[i] + 1
                       for i in range(3)]
        idx = np.asarray(x._indices)
        coords = set()
        kernel = np.asarray(self._kernel)
        stride = np.asarray(self._stride)
        pad = np.asarray(self._padding)
        for e in range(idx.shape[1]):
            b = int(idx[0, e])
            pos = idx[1:4, e] + pad
            # every window covering pos: o*stride <= pos < o*stride + kernel
            lo = np.maximum(0, -(-(pos - kernel + 1) // stride))  # ceil div
            hi = np.minimum(np.asarray(out_spatial) - 1, pos // stride)
            if np.any(lo > hi):
                continue
            for od in range(int(lo[0]), int(hi[0]) + 1):
                for oh in range(int(lo[1]), int(hi[1]) + 1):
                    for ow in range(int(lo[2]), int(hi[2]) + 1):
                        coords.add((b, od, oh, ow))
        coords = sorted(coords) or [(0, 0, 0, 0)]
        out_coords = jnp.asarray(np.asarray(coords, np.int32).T)
        gather_idx = tuple(out_coords[i] for i in range(4))
        kernel, stride, padding = self._kernel, self._stride, self._padding
        scatter_idx = tuple(x._indices[i] for i in range(4))
        dense_shape = tuple(x._shape)

        def impl(vals_in):
            # densify onto -inf so inactive voxels never win the max
            # (sparse max-pool reduces over active sites only)
            neg_inf = jnp.finfo(vals_in.dtype).min
            dv = jnp.full(dense_shape, neg_inf, vals_in.dtype)
            dv = dv.at[scatter_idx].max(vals_in)
            out = jax.lax.reduce_window(
                dv, neg_inf, jax.lax.max,
                window_dimensions=(1, *kernel, 1),
                window_strides=(1, *stride, 1),
                padding=((0, 0), *[(p, p) for p in padding], (0, 0)))
            return out[gather_idx]
        vals = call_op(impl, x.values())
        out_shape = (x._shape[0],) + tuple(out_spatial) + (x._shape[4],)
        return SparseCooTensor(out_coords, vals, out_shape)


class functional:
    """paddle.sparse.nn.functional"""
    relu = staticmethod(_relu_fn)
    relu6 = staticmethod(_relu6_fn)
    leaky_relu = staticmethod(_lrelu_fn)
    softmax = staticmethod(_softmax_fn)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """Sparse-mask attention: scores only at mask nonzeros (SDDMM) →
        sparse softmax → spmm (reference:
        paddle/phi/kernels/sparse/gpu/fused_attention_kernel.cu).

        ``key_padding_mask``: [seq_k] with 0 at padded keys (those positions
        get -inf score); ``attn_mask``: additive [seq_q, seq_k]."""
        from . import masked_matmul, matmul as sp_matmul, SparseCooTensor
        import math as _math
        d = int(query.shape[-1])
        if len(query.shape) != 2:
            raise ValueError("functional.attention here takes 2-D q/k/v "
                             "[seq, dim] per head")
        kt = call_op(lambda v: v.T, key)
        scores = masked_matmul(
            call_op(lambda q: q / _math.sqrt(d), query), kt, sparse_mask)
        if key_padding_mask is not None or attn_mask is not None:
            if isinstance(scores, SparseCooTensor):
                rows, cols = scores._indices[0], scores._indices[1]
            else:
                rows, cols = scores._row_ids(), scores._cols
            kp = (key_padding_mask._value
                  if hasattr(key_padding_mask, "_value")
                  else key_padding_mask)
            am = (attn_mask._value if hasattr(attn_mask, "_value")
                  else attn_mask)

            def mask_impl(v):
                if kp is not None:
                    v = jnp.where(jnp.asarray(kp)[cols] != 0, v, -1e9)
                if am is not None:
                    v = v + jnp.asarray(am)[rows, cols]
                return v
            new_vals = call_op(mask_impl, scores._values)
            if isinstance(scores, SparseCooTensor):
                scores = SparseCooTensor(scores._indices, new_vals,
                                         scores._shape, scores._coalesced)
            else:
                from . import SparseCsrTensor
                scores = SparseCsrTensor(scores._crows, scores._cols,
                                         new_vals, scores._shape)
        probs = _softmax_fn(scores)
        return sp_matmul(probs, value)
