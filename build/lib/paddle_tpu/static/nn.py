"""paddle.static.nn control-flow ops (reference:
python/paddle/static/nn/control_flow.py — cond builds a
conditional_block pair, while_loop builds a While op with a sub-block).

TPU-native: both delegate to the jit.dy2static runtime converters, so a
concrete predicate keeps Python semantics and a traced predicate lowers
to ``lax.cond`` / ``lax.while_loop`` — the same machinery the AST pass
uses, exposed as the explicit user API.
"""
from ..framework.core import Tensor
from ..jit.dy2static import convert_ifelse, convert_while_loop

__all__ = ["cond", "while_loop"]


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run ``true_fn()`` or ``false_fn()`` depending on ``pred``.

    Both callables take no arguments and must return structurally
    matching outputs (lax.cond contract when traced).  A missing branch
    behaves as ``lambda: None``.
    """
    t = true_fn if true_fn is not None else (lambda: None)
    f = false_fn if false_fn is not None else (lambda: None)
    return convert_ifelse(pred, lambda *_: t(), lambda *_: f())


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Repeat ``body(*loop_vars)`` while ``cond(*loop_vars)`` holds.

    ``body`` must return the next loop_vars (list/tuple, same structure
    and shapes).  Returns the final loop_vars as a list, like the
    reference API.
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")

    def body_tuple(*vs):
        out = body(*vs)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        if len(out) != len(loop_vars):
            raise ValueError(
                f"body returned {len(out)} vars, expected {len(loop_vars)}")
        return tuple(out)

    out = convert_while_loop(cond, body_tuple, tuple(loop_vars))
    return list(out)
