"""Shared machinery for the eager op surface.

Reference analogue: the Phi kernel library + dispatch
(paddle/phi/kernels/, paddle/phi/core/kernel_factory.cc).  TPU-native: every
op is a jnp/lax lambda run through the autograd tape (`call_op`); XLA is the
kernel library, so there is no per-backend registry — one definition serves
CPU and TPU, eager and traced.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import call_op
from ..framework import dtypes


def ensure_tensor(x, ref_dtype=None):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (int, float, bool, complex)):
        # keep python scalars weakly typed via closure-free asarray
        return Tensor(jnp.asarray(x))
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        # raw jax values (incl. tracers inside lax control flow, which
        # np.asarray would try to concretize) wrap directly
        return Tensor(x)
    arr = np.asarray(x)
    if arr.dtype == np.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    return Tensor(arr)


def unary_op(fn):
    def op(x, name=None):
        return call_op(fn, ensure_tensor(x))
    return op


def binary_op(fn):
    def op(x, y, name=None):
        return call_op(fn, ensure_tensor(x), ensure_tensor(y))
    return op


def reduce_op(fn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = ensure_tensor(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(int(a) for a in axis)
        elif axis is not None and not isinstance(axis, int):
            axis = int(axis)
        kw = dict(axis=axis, keepdims=keepdim)
        if dtype is not None:
            kw["dtype"] = dtypes.convert_dtype(dtype)
        return call_op(lambda v: fn(v, **kw), x)
    return op


def raw(x):
    """Underlying jax array of a Tensor (or pass-through)."""
    return x._value if isinstance(x, Tensor) else x
