"""Einsum (reference: python/paddle/tensor/einsum.py — a hand-written
planner over matmul/reduce ops; here jnp.einsum lowers straight to MXU
dot_generals via XLA)."""
import jax.numpy as jnp

from ..framework.autograd import call_op
from ._helpers import ensure_tensor


def einsum(equation, *operands, name=None):
    ts = [ensure_tensor(o) for o in operands]
    return call_op(lambda *vs: jnp.einsum(equation, *vs), *ts)
