"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
import jax.numpy as jnp

from ..framework.autograd import call_op
from ._helpers import ensure_tensor
from .math import mean  # noqa: F401 (re-export)


def _axis(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.std(v, axis=_axis(axis),
                                     ddof=1 if unbiased else 0,
                                     keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.var(v, axis=_axis(axis),
                                     ddof=1 if unbiased else 0,
                                     keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.median(v, axis=_axis(axis),
                                        keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.nanmedian(v, axis=_axis(axis),
                                           keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_axis(axis),
                                          keepdims=keepdim,
                                          method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return call_op(lambda v: jnp.nanquantile(v, jnp.asarray(q),
                                             axis=_axis(axis),
                                             keepdims=keepdim), x)
