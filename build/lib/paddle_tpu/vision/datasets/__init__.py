"""Vision datasets (reference: python/paddle/vision/datasets/).

No network egress: each dataset loads from a local file when present
(paddle's cache layout) and otherwise generates a deterministic synthetic
stand-in with identical shapes/dtypes/types so every pipeline runs
end-to-end (clearly flagged via ``.synthetic``).
"""
import os

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "DatasetFolder", "ImageFolder"]


class _SyntheticImageDataset(Dataset):
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10
    TRAIN_N = 60000
    TEST_N = 10000
    SYN_TRAIN_N = 2048
    SYN_TEST_N = 512

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        self.backend = backend or "cv2"
        self.synthetic = True
        n = self.SYN_TRAIN_N if self.mode == "train" else self.SYN_TEST_N
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        c, h, w = self.IMAGE_SHAPE
        self.labels = rng.randint(0, self.NUM_CLASSES, size=(n,)).astype(
            "int64")
        # class-dependent means so models can actually learn
        base = rng.rand(self.NUM_CLASSES, c, h, w).astype("float32")
        noise = rng.rand(n, c, h, w).astype("float32") * 0.5
        self.images = (base[self.labels] + noise).astype("float32")

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype="int64")
        if self.backend == "cv2":
            img_out = np.transpose(img, (1, 2, 0))
        else:
            img_out = img
        if self.transform is not None:
            img_out = self.transform(img_out)
        return img_out, label

    def __len__(self):
        return len(self.images)


class MNIST(_SyntheticImageDataset):
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10


class FashionMNIST(_SyntheticImageDataset):
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10


class Cifar10(_SyntheticImageDataset):
    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(None, None, mode, transform, download, backend)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(_SyntheticImageDataset):
    """102-class flowers (reference:
    python/paddle/vision/datasets/flowers.py)."""
    IMAGE_SHAPE = (3, 64, 64)
    NUM_CLASSES = 102
    SYN_TRAIN_N = 1024
    SYN_TEST_N = 256

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        super().__init__(None, None, mode, transform, download, backend)


class VOC2012(Dataset):
    """Segmentation pairs (image, mask) (reference:
    python/paddle/vision/datasets/voc2012.py)."""
    IMAGE_SHAPE = (3, 64, 64)
    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        self.backend = backend or "cv2"
        self.synthetic = True
        n = 256 if self.mode == "train" else 64
        rng = np.random.RandomState(7 if self.mode == "train" else 8)
        c, h, w = self.IMAGE_SHAPE
        self.images = rng.rand(n, c, h, w).astype("float32")
        # blocky masks correlated with image intensity
        self.masks = (self.images.mean(1) * self.NUM_CLASSES).astype(
            "int64") % self.NUM_CLASSES

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.backend == "cv2":
            img = np.transpose(img, (1, 2, 0))
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


class DatasetFolder(Dataset):
    """Directory-of-class-folders dataset (reference:
    python/paddle/vision/datasets/folder.py).  Loads real files via numpy
    (.npy) or falls back to flat binary reads — no PIL in this image."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root}")

    @staticmethod
    def _default_loader(path):
        return np.load(path)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Unlabeled flat folder variant."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy",)
        self.samples = []
        for fn in sorted(os.listdir(root)):
            path = os.path.join(root, fn)
            if not os.path.isfile(path):
                continue
            ok = (is_valid_file(path) if is_valid_file
                  else fn.lower().endswith(tuple(extensions)))
            if ok:
                self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
