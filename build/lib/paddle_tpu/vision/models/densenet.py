"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from ... import nn
from ...tensor.manipulation import concat, flatten

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.drop_rate = drop_rate
        if drop_rate > 0:
            self.dropout = nn.Dropout(drop_rate)

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.drop_rate > 0:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate,
                 drop_rate):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_input_features + i * growth_rate, growth_rate,
                        bn_size, drop_rate) for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_input_features, num_output_features, 1,
                              bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        block_cfg = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                     169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                     264: (6, 12, 64, 48)}[layers]
        num_init_features = 2 * growth_rate if layers == 161 else 64
        if layers == 161:
            growth_rate = 48
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                               bias_attr=False)
        self.norm1 = nn.BatchNorm2D(num_init_features)
        self.relu = nn.ReLU()
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        num_features = num_init_features
        for i, num_layers in enumerate(block_cfg):
            blocks.append(_DenseBlock(num_layers, num_features, bn_size,
                                      growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(num_features)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.pool1(self.relu(self.norm1(self.conv1(x))))
        x = self.relu(self.norm_final(self.blocks(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
