"""GoogLeNet + InceptionV3 (reference: python/paddle/vision/models/
googlenet.py, inceptionv3.py)."""
from ... import nn
from ...tensor.manipulation import concat, flatten

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


class _BasicConv2d(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.branch1 = _BasicConv2d(in_c, c1, 1)
        self.branch2 = nn.Sequential(_BasicConv2d(in_c, c3r, 1),
                                     _BasicConv2d(c3r, c3, 3, padding=1))
        self.branch3 = nn.Sequential(_BasicConv2d(in_c, c5r, 1),
                                     _BasicConv2d(c5r, c5, 5, padding=2))
        self.branch4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                     _BasicConv2d(in_c, proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _BasicConv2d(3, 64, 7, stride=2, padding=3)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        self.conv2 = _BasicConv2d(64, 64, 1)
        self.conv3 = _BasicConv2d(64, 192, 3, padding=1)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inception3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inception4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inception4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inception4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inception4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool5 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inception5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inception5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.pool3(self.conv3(self.conv2(x)))
        x = self.pool4(self.inception3b(self.inception3a(x)))
        x = self.inception4e(self.inception4d(self.inception4c(
            self.inception4b(self.inception4a(x)))))
        x = self.pool5(x)
        x = self.inception5b(self.inception5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return GoogLeNet(**kwargs)


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.branch1x1 = _BasicConv2d(in_c, 64, 1)
        self.branch5x5 = nn.Sequential(_BasicConv2d(in_c, 48, 1),
                                       _BasicConv2d(48, 64, 5, padding=2))
        self.branch3x3dbl = nn.Sequential(
            _BasicConv2d(in_c, 64, 1), _BasicConv2d(64, 96, 3, padding=1),
            _BasicConv2d(96, 96, 3, padding=1))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1),
            _BasicConv2d(in_c, pool_features, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch5x5(x),
                       self.branch3x3dbl(x), self.branch_pool(x)], axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch3x3 = _BasicConv2d(in_c, 384, 3, stride=2)
        self.branch3x3dbl = nn.Sequential(
            _BasicConv2d(in_c, 64, 1), _BasicConv2d(64, 96, 3, padding=1),
            _BasicConv2d(96, 96, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch3x3dbl(x),
                       self.branch_pool(x)], axis=1)


class _Conv1xN(nn.Layer):
    """1x7 then 7x1 factorized conv pair."""

    def __init__(self, in_c, mid, out_c, n=7):
        super().__init__()
        p = n // 2
        self.a = _BasicConv2d(in_c, mid, (1, n), padding=(0, p))
        self.b = _BasicConv2d(mid, out_c, (n, 1), padding=(p, 0))

    def forward(self, x):
        return self.b(self.a(x))


class _InceptionC(nn.Layer):
    def __init__(self, in_c, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = _BasicConv2d(in_c, 192, 1)
        self.branch7x7 = nn.Sequential(_BasicConv2d(in_c, c7, 1),
                                       _Conv1xN(c7, c7, 192))
        self.branch7x7dbl = nn.Sequential(
            _BasicConv2d(in_c, c7, 1), _Conv1xN(c7, c7, c7),
            _Conv1xN(c7, c7, 192))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1), _BasicConv2d(in_c, 192, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch7x7(x),
                       self.branch7x7dbl(x), self.branch_pool(x)], axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch3x3 = nn.Sequential(_BasicConv2d(in_c, 192, 1),
                                       _BasicConv2d(192, 320, 3, stride=2))
        self.branch7x7x3 = nn.Sequential(
            _BasicConv2d(in_c, 192, 1), _Conv1xN(192, 192, 192),
            _BasicConv2d(192, 192, 3, stride=2))
        self.branch_pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch7x7x3(x),
                       self.branch_pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.branch1x1 = _BasicConv2d(in_c, 320, 1)
        self.branch3x3_1 = _BasicConv2d(in_c, 384, 1)
        self.branch3x3_2a = _BasicConv2d(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = _BasicConv2d(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = nn.Sequential(
            _BasicConv2d(in_c, 448, 1), _BasicConv2d(448, 384, 3, padding=1))
        self.branch3x3dbl_3a = _BasicConv2d(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = _BasicConv2d(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = nn.Sequential(
            nn.AvgPool2D(3, stride=1, padding=1), _BasicConv2d(in_c, 192, 1))

    def forward(self, x):
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=1)
        bd = self.branch3x3dbl_1(x)
        bd = concat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)],
                    axis=1)
        return concat([b1, b3, bd, self.branch_pool(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv2d(3, 32, 3, stride=2), _BasicConv2d(32, 32, 3),
            _BasicConv2d(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _BasicConv2d(64, 80, 1), _BasicConv2d(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.inception_block = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.inception_block(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return InceptionV3(**kwargs)
