"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/
mobilenetv1.py, mobilenetv2.py, mobilenetv3.py)."""
from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        acts = {"relu": nn.ReLU(), "relu6": nn.ReLU6(),
                "hardswish": nn.Hardswish(), None: nn.Identity()}
        self.act = acts[act]

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(in_c, int(out_c1 * scale), 3, stride=stride,
                              padding=1, groups=in_c)
        self.pw = ConvBNLayer(int(out_c1 * scale), int(out_c2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
               (1024, 1024, 1024, 1)]
        blocks = [DepthwiseSeparable(int(i * scale), o1, o2, s, scale)
                  for i, o1, o2, s in cfg]
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden_dim, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden_dim, hidden_dim, 3, stride=stride, padding=1,
                        groups=hidden_dim, act="relu6"),
            ConvBNLayer(hidden_dim, oup, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res_connect else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = _make_divisible(32 * scale)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        features = [ConvBNLayer(3, input_channel, 3, stride=2, padding=1,
                                act="relu6")]
        for t, c, n, s in cfg:
            output_channel = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, output_channel, s if i == 0 else 1, t))
                input_channel = output_channel
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1,
                                    act="relu6"))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


class SqueezeExcitation(nn.Layer):
    def __init__(self, channel, reduction=4):
        super().__init__()
        squeeze = _make_divisible(channel // reduction)
        self.avg_pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channel, squeeze, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze, channel, 1)
        self.hsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        s = self.avg_pool(x)
        s = self.hsigmoid(self.fc2(self.relu(self.fc1(s))))
        return x * s


class InvertedResidualV3(nn.Layer):
    def __init__(self, inp, hidden, oup, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        layers = []
        if hidden != inp:
            layers.append(ConvBNLayer(inp, hidden, 1, act=act))
        layers.append(ConvBNLayer(hidden, hidden, kernel, stride=stride,
                                  padding=kernel // 2, groups=hidden,
                                  act=act))
        if use_se:
            layers.append(SqueezeExcitation(hidden))
        layers.append(ConvBNLayer(hidden, oup, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                              act="hardswish")]
        for k, exp, c, se, act, s in cfg:
            out_c = _make_divisible(c * scale)
            hid = _make_divisible(exp * scale)
            layers.append(InvertedResidualV3(in_c, hid, out_c, k, s, se,
                                             act))
            in_c = out_c
        last_conv = _make_divisible(cfg[-1][1] * scale)
        layers.append(ConvBNLayer(in_c, last_conv, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            # k, exp, c, se, act, s
            (3, 16, 16, True, "relu", 2),
            (3, 72, 24, False, "relu", 2),
            (3, 88, 24, False, "relu", 1),
            (5, 96, 40, True, "hardswish", 2),
            (5, 240, 40, True, "hardswish", 1),
            (5, 240, 40, True, "hardswish", 1),
            (5, 120, 48, True, "hardswish", 1),
            (5, 144, 48, True, "hardswish", 1),
            (5, 288, 96, True, "hardswish", 2),
            (5, 576, 96, True, "hardswish", 1),
            (5, 576, 96, True, "hardswish", 1),
        ]
        super().__init__(cfg, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, False, "relu", 1),
            (3, 64, 24, False, "relu", 2),
            (3, 72, 24, False, "relu", 1),
            (5, 72, 40, True, "relu", 2),
            (5, 120, 40, True, "relu", 1),
            (5, 120, 40, True, "relu", 1),
            (3, 240, 80, False, "hardswish", 2),
            (3, 200, 80, False, "hardswish", 1),
            (3, 184, 80, False, "hardswish", 1),
            (3, 184, 80, False, "hardswish", 1),
            (3, 480, 112, True, "hardswish", 1),
            (3, 672, 112, True, "hardswish", 1),
            (5, 672, 160, True, "hardswish", 2),
            (5, 960, 160, True, "hardswish", 1),
            (5, 960, 160, True, "hardswish", 1),
        ]
        super().__init__(cfg, 1280, scale, num_classes, with_pool)


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)
