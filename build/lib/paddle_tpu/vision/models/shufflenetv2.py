"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from ... import nn
from ...tensor.manipulation import concat, flatten, reshape, transpose, split

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU(), "swish": nn.Swish(),
                    None: nn.Identity()}[act]

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _ConvBNAct(branch_c, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride=1, padding=1,
                           groups=branch_c, act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                _ConvBNAct(in_c, in_c, 3, stride=stride, padding=1,
                           groups=in_c, act=None),
                _ConvBNAct(in_c, branch_c, 1, act=act))
            self.branch2 = nn.Sequential(
                _ConvBNAct(in_c, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride=stride, padding=1,
                           groups=branch_c, act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        channels = {
            0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
            0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
            1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
        }[scale]
        self.conv1 = _ConvBNAct(3, channels[0], 3, stride=2, padding=1,
                                act=act)
        self.max_pool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        blocks = []
        in_c = channels[0]
        for stage, repeats in enumerate(stage_repeats):
            out_c = channels[stage + 1]
            for i in range(repeats):
                blocks.append(InvertedResidual(in_c, out_c,
                                               2 if i == 0 else 1, act))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = _ConvBNAct(in_c, channels[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.conv_last(self.blocks(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
