"""SqueezeNet + AlexNet (reference: python/paddle/vision/models/
squeezenet.py, alexnet.py)."""
from ... import nn
from ...tensor.manipulation import concat, flatten

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1", "AlexNet",
           "alexnet"]


class Fire(nn.Layer):
    def __init__(self, inplanes, squeeze_planes, expand1x1_planes,
                 expand3x3_planes):
        super().__init__()
        self.squeeze = nn.Conv2D(inplanes, squeeze_planes, 1)
        self.relu = nn.ReLU()
        self.expand1x1 = nn.Conv2D(squeeze_planes, expand1x1_planes, 1)
        self.expand3x3 = nn.Conv2D(squeeze_planes, expand3x3_planes, 3,
                                   padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1x1(x)),
                       self.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), Fire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
            x = flatten(x, 1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return SqueezeNet("1.1", **kwargs)


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def alexnet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained unavailable offline; use paddle.load")
    return AlexNet(**kwargs)
