"""Vision ops (reference: python/paddle/vision/ops.py — roi_align, nms,
deform_conv2d CUDA kernels).  XLA-composable implementations."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import call_op
from ..tensor._helpers import ensure_tensor

__all__ = ["nms", "roi_align", "box_coder", "yolo_box", "deform_conv2d",
           "roi_pool", "psroi_pool", "DeformConv2D"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    import numpy as np
    b = np.asarray(ensure_tensor(boxes)._value)
    s = np.asarray(ensure_tensor(scores)._value) if scores is not None \
        else np.arange(len(b))[::-1].astype("float32")
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = ((b[order[1:], 2] - b[order[1:], 0]) *
                  (b[order[1:], 3] - b[order[1:], 1]))
        iou = inter / (area_i + area_o - inter + 1e-9)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, dtype="int64")
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _ra(feat, bxs):
        N, C, H, W = feat.shape
        offset = 0.5 if aligned else 0.0

        def one_box(box):
            x1, y1, x2, y2 = box * spatial_scale - offset
            bw = jnp.maximum(x2 - x1, 1.0)
            bh = jnp.maximum(y2 - y1, 1.0)
            ys = y1 + (jnp.arange(oh) + 0.5) * bh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * bw / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            f = feat[0]
            v = (f[:, y0, x0] * (1 - wy) * (1 - wx) +
                 f[:, y1i, x0] * wy * (1 - wx) +
                 f[:, y0, x1i] * (1 - wy) * wx +
                 f[:, y1i, x1i] * wy * wx)
            return v
        return jax.vmap(one_box)(bxs)
    return call_op(_ra, x, boxes)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode",
              box_normalized=True, axis=0, name=None):
    raise NotImplementedError("box_coder lands with the detection suite")


def yolo_box(*args, **kwargs):
    raise NotImplementedError("yolo_box lands with the detection suite")


def _bilinear_sample(img, y, x):
    """img [C,H,W]; y/x arbitrary same-shaped float coords → [C, *coords].
    Zero padding outside (reference deform-conv border handling)."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def tap(yi, xi, w):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        vals = img[:, yc, xc]                    # [C, *coords]
        return vals * (w * valid)[None]
    return (tap(y0, x0, wy0 * wx0) + tap(y0, x1, wy0 * wx1) +
            tap(y1, x0, wy1 * wx0) + tap(y1, x1, wy1 * wx1))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: python/paddle/vision/ops.py
    deform_conv2d over paddle/phi/kernels/gpu/deformable_conv_kernel.cu).

    TPU-native: bilinear gather at offset sample points (vectorized over
    batch/taps with vmap — XLA lowers to gathers) followed by one big
    matmul over (C_in·K) — the im2col+GEMM formulation on the MXU.
    x: [N,C,H,W]; offset: [N, 2·K·dg, Ho, Wo]; weight: [Co, C/groups, kh,
    kw]; mask (v2): [N, K·dg, Ho, Wo].
    """
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1 not "
                                  "supported yet")
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    ts = [ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)]
    if mask is not None:
        ts.append(ensure_tensor(mask))
    if bias is not None:
        ts.append(ensure_tensor(bias))
    has_mask = mask is not None
    has_bias = bias is not None

    def impl(xv, offv, wv, *rest):
        mv = rest[0] if has_mask else None
        bv = rest[-1] if has_bias else None
        N, C, H, W = xv.shape
        Co, Ci, kh, kw = wv.shape
        K = kh * kw
        Ho = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        Wo = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        # base sampling grid per tap: [K, Ho, Wo]
        oy, ox = jnp.meshgrid(jnp.arange(Ho), jnp.arange(Wo), indexing="ij")
        ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
        base_y = (oy[None] * stride[0] - padding[0]
                  + ky.reshape(-1)[:, None, None] * dilation[0])
        base_x = (ox[None] * stride[1] - padding[1]
                  + kx.reshape(-1)[:, None, None] * dilation[1])
        off = offv.reshape(N, K, 2, Ho, Wo)     # paddle layout: (dy, dx)
        sy = base_y[None] + off[:, :, 0]
        sx = base_x[None] + off[:, :, 1]        # [N, K, Ho, Wo]

        def per_image(img, yy, xx, m):
            samples = _bilinear_sample(img, yy, xx)   # [C, K, Ho, Wo]
            if m is not None:
                samples = samples * m[None]
            return samples
        if mv is not None:
            mk = mv.reshape(N, K, Ho, Wo)
            samples = jax.vmap(per_image)(xv, sy, sx, mk)
        else:
            samples = jax.vmap(lambda i, a, b: per_image(i, a, b, None))(
                xv, sy, sx)
        # [N, C, K, Ho, Wo] × [Co, C, K] → [N, Co, Ho, Wo]  (one GEMM)
        out = jnp.einsum("nckhw,ock->nohw", samples,
                         wv.reshape(Co, Ci, K),
                         preferred_element_type=jnp.float32)
        out = out.astype(xv.dtype)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out
    return call_op(impl, *ts)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max ROI pooling (reference: ops.roi_pool).  boxes: [R, 4] xyxy.

    Implementation note: each output bin reduces a full-map mask, costing
    ph·pw full passes per ROI.  This preserves the reference's
    floor/ceil OVERLAPPING bin boundaries exactly; a single-pass
    segment-reduce would be ~ph·pw× cheaper but assigns boundary pixels
    to one bin only, silently diverging from the reference at bin edges.
    ROI ops are not on this framework's hot path, so exactness wins."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def impl(xv, bv):
        # single-image path (boxes_num per-image batching: image 0)
        N, C, H, W = xv.shape
        if N != 1:
            raise NotImplementedError(
                "roi_pool currently supports a single image per call; "
                "split the batch and concatenate results")

        def one_box(box):
            x1, y1, x2, y2 = [box[i] * spatial_scale for i in range(4)]
            x1, y1 = jnp.round(x1), jnp.round(y1)
            x2, y2 = jnp.round(x2), jnp.round(y2)
            bw = jnp.maximum(x2 - x1 + 1, 1.0)
            bh = jnp.maximum(y2 - y1 + 1, 1.0)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)
            out = jnp.zeros((C, ph, pw), xv.dtype)
            for i in range(ph):
                for j in range(pw):
                    hs = jnp.floor(y1 + bh * i / ph)
                    he = jnp.ceil(y1 + bh * (i + 1) / ph)
                    ws = jnp.floor(x1 + bw * j / pw)
                    we = jnp.ceil(x1 + bw * (j + 1) / pw)
                    row_m = (ys >= hs) & (ys < he)
                    col_m = (xs >= ws) & (xs < we)
                    m = row_m[:, None] & col_m[None, :]
                    lowest = (jnp.finfo(xv.dtype).min
                              if jnp.issubdtype(xv.dtype, jnp.floating)
                              else jnp.iinfo(xv.dtype).min)
                    cell = jnp.where(m[None], xv[0], lowest)
                    val = cell.max(axis=(1, 2))
                    val = jnp.where(m.any(), val, 0.0)
                    out = out.at[:, i, j].set(val)
            return out
        return jax.vmap(one_box)(bv)
    return call_op(impl, ensure_tensor(x), ensure_tensor(boxes))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive ROI pooling (reference: ops.psroi_pool): input
    channels C = out_c·ph·pw; bin (i,j) averages channel block (i·pw+j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def impl(xv, bv):
        N, C, H, W = xv.shape
        if N != 1:
            raise NotImplementedError(
                "psroi_pool currently supports a single image per call; "
                "split the batch and concatenate results")
        if C % (ph * pw) != 0 or C < ph * pw:
            raise ValueError(
                f"psroi_pool needs channels divisible by output h*w "
                f"({ph}*{pw}); got C={C}")
        out_c = C // (ph * pw)

        def one_box(box):
            x1, y1, x2, y2 = [box[i] * spatial_scale for i in range(4)]
            bw = jnp.maximum(x2 - x1, 0.1)
            bh = jnp.maximum(y2 - y1, 0.1)
            ys = jnp.arange(H, dtype=jnp.float32) + 0.5
            xs = jnp.arange(W, dtype=jnp.float32) + 0.5
            out = jnp.zeros((out_c, ph, pw), xv.dtype)
            for i in range(ph):
                for j in range(pw):
                    hs = y1 + bh * i / ph
                    he = y1 + bh * (i + 1) / ph
                    ws = x1 + bw * j / pw
                    we = x1 + bw * (j + 1) / pw
                    m = ((ys >= hs) & (ys < he))[:, None] & \
                        ((xs >= ws) & (xs < we))[None, :]
                    count = jnp.maximum(m.sum(), 1)
                    # channel-major blocks: out channel c reads input
                    # channel c·ph·pw + i·pw + j (R-FCN convention)
                    ch = jnp.arange(out_c) * (ph * pw) + i * pw + j
                    blk = xv[0, ch]
                    val = (blk * m[None]).sum(axis=(1, 2)) / count
                    out = out.at[:, i, j].set(val)
            return out
        return jax.vmap(one_box)(bv)
    return call_op(impl, ensure_tensor(x), ensure_tensor(boxes))


from ..nn.layer.layers import Layer as _Layer
from ..nn import initializer as _I


class DeformConv2D(_Layer):
    """Layer wrapper (reference: paddle.vision.ops.DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        import numpy as _np
        k = 1.0 / float(_np.sqrt(in_channels * ks[0] * ks[1]))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr, default_initializer=_I.Uniform(-k, k))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=_I.Uniform(-k, k))
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)
