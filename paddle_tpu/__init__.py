"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas.

Layer map vs the reference (see SURVEY.md §1/§7): PJRT+XLA replace the
device runtime/allocators/executors; jax tracing+vjp replace the eager
autograd engine; GSPMD/pjit replaces Fleet's hand-built hybrid parallelism;
Pallas kernels replace the CUDA kernel library.
"""
from .framework import dtypes as _dtypes
from .framework.dtypes import (  # noqa: F401
    float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool, complex64, complex128,
    set_default_dtype, get_default_dtype)
from .framework.core import (  # noqa: F401
    Tensor, to_tensor, set_device, get_device, is_tensor,
    set_printoptions)
from .framework.autograd import no_grad, enable_grad, set_grad_enabled, \
    is_grad_enabled, grad  # noqa: F401
from .framework.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework import random as _random

from .tensor import *  # noqa: F401,F403
from .tensor import linalg  # noqa: F401  (paddle.linalg namespace)
from .tensor import creation as _creation

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import distributed  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import device  # noqa: F401
from . import profiler  # noqa: F401
from . import distribution  # noqa: F401
from . import autograd  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import inference  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import regularizer  # noqa: F401
from . import sysconfig  # noqa: F401
from .autograd import PyLayer  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import audio  # noqa: F401
from . import geometric  # noqa: F401
from . import incubate  # noqa: F401
from . import hub  # noqa: F401
from . import utils  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi.model_summary import summary  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .framework.flags import set_flags, get_flags  # noqa: F401

# paddle API aliases
create_parameter = _creation.create_parameter
from .static import enable_static, disable_static  # noqa: F401,E402

CPUPlace = lambda: "cpu"
CUDAPlace = lambda idx=0: f"tpu:{idx}"  # no GPUs; map onto TPU
TPUPlace = lambda idx=0: f"tpu:{idx}"

__version__ = "0.3.0"


def in_dynamic_mode():
    from . import static as _static
    return not _static._static_mode[0]


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    import jax
    return any(d.platform != "cpu" for d in jax.devices())


def is_compiled_with_distribute():
    return True


def is_grad_enabled_():
    return is_grad_enabled()


def get_cudnn_version():
    return None


from . import version  # noqa: F401,E402


def iinfo(dtype):
    """reference: paddle.iinfo."""
    import numpy as _np
    return _np.iinfo(_np.dtype(str(_dtypes.convert_dtype(dtype))))


def finfo(dtype):
    """reference: paddle.finfo."""
    import jax.numpy as _jnp
    return _jnp.finfo(_dtypes.convert_dtype(dtype))


# CUDA-named RNG state entry points map to the device-agnostic RNG
# (reference: get/set_cuda_rng_state; one RNG stream here)
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: paddle.flops — model FLOPs for one forward pass.

    TPU-native: instead of the reference's per-layer-type FLOPs table,
    trace the ACTUAL forward with jax and read XLA's compiled cost
    analysis — counts every op the compiler will run, including fusions
    the table-based counter cannot see."""
    import numpy as _np
    import jax
    import jax.numpy as _jnp
    from .framework import autograd as _ag
    from .framework.random import rng_scope

    x = _jnp.zeros(tuple(input_size), _jnp.float32)
    params = [p for _, p in net.named_parameters()]
    vals = [p._value for p in params]

    def fwd(pv, xv):
        olds = [p._value for p in params]
        for p, v in zip(params, pv):
            p._value = v
        try:
            with _ag.suspend_tape(), rng_scope(jax.random.key(0)):
                out = net(Tensor(xv))
            return out._value if hasattr(out, "_value") else out
        finally:
            for p, v in zip(params, olds):
                p._value = v

    compiled = jax.jit(fwd).lower(vals, x).compile()
    try:
        # only the analysis readout is best-effort — trace/compile
        # errors above are REAL user errors and must propagate
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):   # jax 0.4.x: per-device list
            cost = cost[0] if cost else None
        total = int(cost.get("flops", 0)) if cost else 0
    except Exception:
        total = 0
    if print_detail:
        import builtins
        # NB: plain `sum` here would resolve to paddle.sum (the tensor
        # reduce op star-exported into this module)
        n_params = builtins.sum(int(_np.prod(p.shape)) for p in params)
        print(f"Total Flops: {total}     Total Params: {n_params}")
    return total


def in_static_mode():
    return not in_dynamic_mode()


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(device_name=None):
    # the axon TPU plugin IS a custom PJRT device
    return is_compiled_with_tpu()


def disable_signal_handler():
    """reference: paddle.disable_signal_handler — the reference installs
    C++ fault handlers it sometimes must drop; PJRT installs none, so
    this is a true no-op kept for API parity."""


def batch(reader, batch_size, drop_last=False):
    """reference: paddle.batch — wrap an item reader into a batch
    reader (legacy reader-decorator API)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


class LazyGuard:
    """reference: paddle.LazyGuard — delay parameter initialization.

    Inside the context, ``create_parameter`` skips running the
    initializer (parameters hold zeros of the right shape/dtype and
    remember their initializer); call ``param.initialize()`` — or
    iterate ``layer.parameters()`` calling it — to materialize.  On TPU
    the main win is skipping redundant init compute for params that a
    checkpoint load or a sharded init will overwrite anyway.
    """

    def __enter__(self):
        from .nn.layer import layers as _l
        _l._LAZY_INIT[0] = True
        return self

    def __exit__(self, *exc):
        from .nn.layer import layers as _l
        _l._LAZY_INIT[0] = False
        return False
