"""AMP (reference: python/paddle/amp/{auto_cast,grad_scaler}.py).

TPU-native: bf16 is the native mixed-precision dtype and needs no loss
scaling, so ``auto_cast`` is a dtype-policy context consulted by the op
layer, and ``GradScaler`` keeps the reference's API surface but defaults to
a no-op for bf16 (dynamic scaling still implemented for fp16 parity).
"""
from contextlib import contextmanager

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import dtypes

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "is_auto_cast_enabled", "get_amp_dtype", "autocast_inputs"]

_AMP_STATE = {"enabled": False, "dtype": jnp.bfloat16, "level": "O1",
              "white": frozenset(), "black": frozenset()}

# O1 per-op cast policy (reference: the op lists in
# python/paddle/amp/amp_lists.py / paddle/fluid/eager/amp_utils.h).
# WHITE: matmul-class ops that are fast AND safe in low precision — cast
# their floating inputs down.  BLACK: numerically-sensitive ops
# (exp/log/softmax/norm/loss reductions) — cast their inputs up to fp32.
# Everything else runs in whatever dtype its inputs arrive in (promote).
WHITE_LIST = frozenset({
    "conv2d", "conv3d", "conv1d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose",
    "matmul", "matmul_v2", "mul", "mm", "bmm", "fc", "linear", "einsum",
    "addmm", "attention", "depthwise_conv2d"})
BLACK_LIST = frozenset({
    "exp", "log", "log2", "log10", "log1p", "square", "pow", "rsqrt",
    "mean", "sum", "cos_sim", "softmax", "log_softmax",
    "softmax_with_cross_entropy", "cross_entropy", "nll_loss",
    "sigmoid_cross_entropy_with_logits", "c_softmax_with_cross_entropy",
    "layer_norm", "group_norm", "instance_norm", "batch_norm", "norm",
    "reduce_sum", "cumsum", "logsumexp", "erf", "erfinv", "softplus",
    "log_sigmoid", "margin_cross_entropy", "kldiv_loss", "l1_norm"})


def is_auto_cast_enabled():
    return _AMP_STATE["enabled"]


def get_amp_dtype():
    return _AMP_STATE["dtype"] if _AMP_STATE["enabled"] else None


def get_amp_level():
    return _AMP_STATE["level"]


def _op_target_dtype(op_name):
    """O1 policy: the dtype this op's floating inputs should carry, or
    None to leave them alone."""
    if not _AMP_STATE["enabled"] or _AMP_STATE["level"] != "O1":
        return None
    black = (BLACK_LIST | _AMP_STATE["black"]) - _AMP_STATE["white"]
    white = (WHITE_LIST | _AMP_STATE["white"]) - _AMP_STATE["black"]
    if op_name in black:
        return jnp.float32
    if op_name in white:
        return _AMP_STATE["dtype"]
    return None


def autocast_inputs(op_name, *tensors):
    """Apply the O1 per-op cast policy to a tuple of Tensors (None
    entries pass through).  Casts run through the tape so gradients see
    the cast transpose.  Called by the op layer (linear/matmul/conv/
    softmax/norm/... sites)."""
    tgt = _op_target_dtype(op_name)
    if tgt is None:
        return tensors if len(tensors) != 1 else tensors[0]
    from ..framework.autograd import call_op
    out = []
    for t in tensors:
        if t is not None and isinstance(t, Tensor) \
                and dtypes.is_floating_dtype(t._value.dtype) \
                and t._value.dtype != tgt:
            t = call_op(lambda v, _d=tgt: v.astype(_d), t)
        out.append(t)
    return tuple(out) if len(out) != 1 else out[0]


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = dict(_AMP_STATE)
    _AMP_STATE["enabled"] = enable
    _AMP_STATE["dtype"] = dtypes.convert_dtype(dtype)
    _AMP_STATE["level"] = level
    _AMP_STATE["white"] = frozenset(custom_white_list or ())
    _AMP_STATE["black"] = frozenset(custom_black_list or ())
    try:
        yield
    finally:
        _AMP_STATE.update(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision (master weights kept by the
    optimizer when multi_precision=True)."""
    d = dtypes.convert_dtype(dtype)

    def _cast_model(m):
        for p in m.parameters():
            if dtypes.is_floating_dtype(p._value.dtype):
                p._master = p._value  # fp32 master copy
                p._value = p._value.astype(d)
        return m
    if level == "O2":
        if isinstance(models, (list, tuple)):
            models = type(models)(_cast_model(m) for m in models)
        else:
            models = _cast_model(models)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (no-op by default on TPU/bf16; full dynamic
    scaling for fp16 parity with the reference's GradScaler)."""

    def __init__(self, enable=True, init_loss_scaling=2.**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        # data-parallel group whose ranks must agree on found_inf (set by
        # DP wrappers / users); None = local verdict (world of 1, or
        # GSPMD where grads are already global arrays)
        self._dp_group = None

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Unscale grads and compute ``found_inf`` with ONE fused
        device-side finite-check over the whole grad tree and ONE host
        sync — never a per-param ``bool(jnp.all(...))`` loop.  The
        verdict is all-reduced (AND) across ``_dp_group``'s ranks so
        every data-parallel replica skips in lockstep rather than
        deadlocking/diverging on a locally-NaN grad."""
        if not self._enable:
            return
        from ..framework import guardian as _guardian
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        grads = []
        for p in params:
            if p._grad is not None:
                p._grad = p._grad * inv
                grads.append(p._grad)
        if grads:
            finite = _guardian.tree_all_finite(grads)
            finite = _guardian.all_reduce_finite(finite, self._dp_group)
            self._found_inf = not _guardian._host_bool(finite)
        else:
            self._found_inf = False
        if _guardian._SENTINEL is not None:
            # hand the verdict to the guardian sentinel so the paired
            # Optimizer.step does not re-check the same grads (one host
            # sync per step even with both active)
            _guardian._SENTINEL.note_verdict(not self._found_inf)
        self._unscaled = True

    def step(self, optimizer):
        """Unscale + conditionally step.  Does NOT update the scale —
        call ``update()`` after (reference GradScaler contract)."""
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        self._unscaled = False
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


def is_bfloat16_supported(device=None):
    """reference: paddle.amp.is_bfloat16_supported — always on TPU (the
    MXU's native dtype)."""
    return True


def is_float16_supported(device=None):
    """reference: paddle.amp.is_float16_supported — fp16 compute exists
    on TPU but bf16 is preferred (no loss-scaling needed)."""
    return True


class debugging:
    """paddle.amp.debugging subset (reference:
    python/paddle/amp/debugging.py)."""

    @staticmethod
    def check_numerics(tensor, op_type="", var_name="",
                       debug_mode=None):
        """NaN/Inf check on a tensor; raises on hit (the reference's
        check_numerics op semantics).  Findings go through the guardian
        log (event ``check_numerics``); the ``guardian.check_numerics``
        failpoint (action ``skip`` = skip trusting the tensor) forces a
        trip on clean data so chaos tests can drive this path
        deterministically."""
        from ..framework import failpoints as _fp
        from ..framework import guardian as _guardian
        from ..framework.core import Tensor
        v = tensor._value if isinstance(tensor, Tensor) else tensor
        arr = np.asarray(v)
        if arr.dtype not in (np.float16, np.float32, np.float64):
            # bf16/fp8: cast through f32 for numpy's isnan/isinf.  Never
            # cast native numpy floats — finite f64 above f32-max must
            # not be misreported as Inf.
            arr = np.asarray(jnp.asarray(v).astype(jnp.float32))
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        forced = bool(_fp._ACTIVE and
                      _fp.fire(_guardian.FP_CHECK_NUMERICS) == "skip")
        if n_nan or n_inf or forced:
            _guardian.emit("check_numerics", op_type=str(op_type),
                           var_name=str(var_name), nan_count=n_nan,
                           inf_count=n_inf, forced=forced)
            raise FloatingPointError(
                f"check_numerics: {op_type}/{var_name}: {n_nan} NaN, "
                f"{n_inf} Inf" + (" (failpoint-forced trip)" if forced
                                  else ""))
        return tensor

    @staticmethod
    def enable_operator_stats_collection():
        from ..framework.flags import set_flags
        set_flags({"FLAGS_check_nan_inf": True})

    @staticmethod
    def disable_operator_stats_collection():
        from ..framework.flags import set_flags
        set_flags({"FLAGS_check_nan_inf": False})
