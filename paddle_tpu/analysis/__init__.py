"""Static-analysis suite: tracer-safety, host-sync budget, collective
order, and registry lints over the framework's compiled hot paths.

The framework carries runtime contracts that are invisible to the type
system — "exactly one host sync per step" in ``GradScaler.unscale_``,
"no trace-breaking host calls inside a jitted stepper", "collectives
must execute in the same static order on every rank".  Nothing in
Python stops the next change from reintroducing a ``.item()`` in a
jitted path or a rank-conditional ``barrier()`` that deadlocks a fleet,
so this package checks them at lint time (see T3 / EQuARX in PAPERS.md:
compute/collective overlap wins evaporate when stray host syncs or
misordered collectives sneak into the step).

The AST-based passes, one runner:

- ``tracer-safety``  — walk functions reachable from registered jit
  entry points (:func:`jit_surface`) and flag trace-breaking patterns:
  ``float()``/``int()``/``bool()``/``len()`` on traced values,
  ``.item()``/``.numpy()`` readbacks, ``np.asarray`` on traced values,
  Python ``if``/``while`` on tensor expressions.
- ``host-sync``      — inventory explicit sync sites (``_host_bool``,
  ``np.asarray``, ``.item()``, ``device_get``, ``block_until_ready``)
  in the monitored hot-path modules against a budgeted allowlist
  (:mod:`paddle_tpu.analysis.allowlist`), machine-checking the
  one-sync-per-step contract.
- ``collective-order`` — flag collective calls under rank- or
  data-dependent branches, and ``if``/``else`` arms whose collective
  sequences differ — the classic SPMD deadlock shapes.
- ``donation``      — registered jit surfaces must donate their large
  state-tree arguments; flag use-after-donate, double donation and
  donated-buffer re-entry into a second jit.
- ``retrace-hazard`` — jit cache keys / static args built from
  data-dependent values (unbucketed shapes, computed floats, dict/set
  order); findings carry the ``pt_compile_*`` surface labels, the
  static half of the runtime ``compile_retrace`` sentinel.
- ``concurrency``   — host state mutated from more than one thread
  entry point must be lock-guarded or explicitly thread-confined;
  flag check-then-act on shared queues/free-lists.
- ``failpoint-refs`` / ``guardian-log`` — the registry lints formerly
  living in ``tools/check_failpoints.py`` / ``check_guardian_log.py``,
  folded into the same framework (the tools remain as thin wrappers).
- ``metrics-registry`` — ``pt_<subsystem>_...`` metric names referenced
  by tests/docs must exist in ``observability/catalog.py``, and the
  docs/observability.md catalog table must mirror it row-for-row.

Run everything: ``python -m paddle_tpu.analysis`` (or
``python tools/lint.py``); ``--json`` for machine output; findings
already recorded in ``tools/lint_baseline.json`` are suppressed so only
*new* violations fail the run (exit 1).

This module stays import-light (no jax, no framework modules) so hot
paths can ``from ..analysis import jit_surface`` without cycles.
"""

__all__ = ["jit_surface", "register_jit_surface", "registered_surfaces",
           "main"]

# (module, qualname) pairs registered at import time by the decorator /
# explicit registration below.  The AST passes find surfaces by spotting
# the decorator syntactically, so analysis works on un-imported fixture
# files too; this runtime registry is the source of truth for *nested*
# functions a decorator can't reach (see EXTRA_JIT_SURFACES in
# allowlist.py) and lets tests introspect what is registered.
_JIT_SURFACES = []


def jit_surface(fn=None):
    """Mark a function (or the builder of a nested jitted function) as a
    jit entry point for the tracer-safety pass.  Identity decorator at
    runtime — zero cost; the static pass recognizes it syntactically."""
    def deco(f):
        qn = f.__qualname__.replace(".<locals>", "")
        _JIT_SURFACES.append((f.__module__, qn))
        return f
    return deco(fn) if fn is not None else deco


def register_jit_surface(module, qualname):
    """Explicit registration for functions a decorator can't reach
    (nested defs).  Pair this with an EXTRA_JIT_SURFACES entry in
    allowlist.py so the AST pass sees it too."""
    _JIT_SURFACES.append((module, qualname))


def registered_surfaces():
    return list(_JIT_SURFACES)


def main(argv=None):
    """CLI entry (``python -m paddle_tpu.analysis``)."""
    from .runner import main as _main
    return _main(argv)
