"""Analysis configuration: monitored hot-path modules, the host-sync
budget allowlist, and extra jit surfaces the decorator can't annotate.

This file is the *policy*; the passes are the mechanism.  Adding a new
host sync to a hot path means adding an entry HERE with a reason —
that's the point: the diff review sees the contract change explicitly
instead of a silent ``.item()`` slipping into the step.
"""

# -- host-sync budget (host_sync.py) ---------------------------------------
#
# Modules whose functions form the per-step hot path.  Every sync-
# primitive call site inside them must match an allowlist entry below,
# with a per-function budget (max sites of that callee per function).
MONITORED_MODULES = (
    "paddle_tpu/framework/guardian.py",
    "paddle_tpu/amp/__init__.py",
    "paddle_tpu/hapi/model.py",
    "paddle_tpu/optimizer/optimizer.py",
    "paddle_tpu/inference/serving.py",
    # paged-KV host-side manager: allocator/prefix bookkeeping between
    # compiled dispatches — the admission-time prompt ingest is the one
    # budgeted site; a device READBACK here is always a bug
    "paddle_tpu/inference/kvcache.py",
    # speculative decoding: everything hot is inside the compiled
    # draft-verify chunk — the one budgeted sync is the standalone
    # entry's prompt ingest; a readback here is always a bug
    "paddle_tpu/inference/speculative.py",
    # fleet router: pure host-side scheduling between engine dispatches
    # — the one budgeted sync is submit's prompt ingest; routing,
    # admission control and health checks must NEVER read the device
    "paddle_tpu/inference/router.py",
    # prefill/decode handoff coordinator: protocol state machine only —
    # the ONE device readback (bundle export) lives in kvcache.py, so
    # this module is monitored with zero allowlist entries
    "paddle_tpu/inference/handoff.py",
    # the bucketed/quantized gradient reducer runs entirely inside the
    # compiled step — ANY sync primitive appearing here is a bug, so it
    # is monitored with zero allowlist entries
    "paddle_tpu/distributed/grad_comm.py",
    # the telemetry layer records from every hot path, so the whole
    # package is monitored: metric recording must NEVER read the
    # device — the one legal sync is the exporter's funnel below
    "paddle_tpu/observability/metrics.py",
    "paddle_tpu/observability/export.py",
    "paddle_tpu/observability/timeline.py",
    "paddle_tpu/observability/catalog.py",
    # compile telemetry + request tracing (ISSUE 10): both record
    # around hot dispatch paths, so a readback in either is always a
    # bug — monitored with ZERO allowlist entries (compile stats come
    # from lowering metadata, trace spans from host clocks the engine
    # already owned)
    "paddle_tpu/observability/compilestats.py",
    "paddle_tpu/observability/tracing.py",
    # flight recorder + watchdog + doctor (ISSUE 13): samples are host
    # dicts recorded at pre-existing sync points, rule evaluation reads
    # only those host values, and doctor parses files — a device
    # readback in any of them is always a bug, so all three are
    # monitored with ZERO allowlist entries
    "paddle_tpu/observability/flight.py",
    "paddle_tpu/observability/watch.py",
    "paddle_tpu/observability/doctor.py",
    # HBM memory ledger (ISSUE 20): the live-buffer census runs at the
    # same pre-existing sync points the flight recorder uses and reads
    # only host metadata (.nbytes/shape off live arrays + the page
    # pool's own counters) — a device readback here is always a bug, so
    # the module is monitored with ZERO allowlist entries
    "paddle_tpu/observability/memory.py",
)

# Call terminals that force (or mark) a device->host sync.
SYNC_CALLEES = frozenset({
    "_host_bool",           # guardian's counted sync funnel
    "item", "numpy", "tolist",
    "device_get",
    "block_until_ready",
})
# numpy-namespace calls that materialize an array on host
NUMPY_SYNC_FUNCS = frozenset({"asarray", "array"})

# (relpath, function qualname, callee) -> {"max": N, "reason": str}
#
# The one-sync-per-step contract (PR 2): the step path may read back at
# most ONE fused finite-verdict; everything else below is an off-step
# path (trip attribution, rollback, eval/debug sinks) and says so.
HOST_SYNC_ALLOWLIST = {
    # guardian: the sync funnel itself + the two step-path verdict reads
    ("paddle_tpu/framework/guardian.py",
     "NumericSentinel.grads_ok", "_host_bool"):
        {"max": 1, "reason": "THE eager-path verdict read: one fused "
                             "finite-check, one sync per step"},
    ("paddle_tpu/framework/guardian.py",
     "TrainingGuardian.after_step", "_host_bool"):
        {"max": 1, "reason": "THE jit-path verdict read (stepper's ok "
                             "flag): one sync per step"},
    ("paddle_tpu/framework/guardian.py",
     "attribute_nonfinite", "asarray"):
        {"max": 1, "reason": "trip path only: per-tensor attribution is "
                             "host-side by design (rare)"},
    ("paddle_tpu/framework/guardian.py", "TrainingGuardian._rollback",
     "asarray"):
        {"max": 1, "reason": "rollback path only: restored-step readback"},
    # amp: the unscale_ contract sync + the debugging API (sync by design)
    ("paddle_tpu/amp/__init__.py", "GradScaler.unscale_", "_host_bool"):
        {"max": 1, "reason": "the PR 2 contract: exactly one host sync "
                             "per unscale_, any parameter count"},
    ("paddle_tpu/amp/__init__.py", "debugging.check_numerics", "asarray"):
        {"max": 2, "reason": "debugging API: host readback is its job "
                             "(never on the compiled step path)"},
    # hapi: H2D ingest + accumulation-path verdict + eval/debug sinks
    ("paddle_tpu/hapi/model.py", "_to_jnp", "asarray"):
        {"max": 1, "reason": "H2D ingest of host batches (numpy->device), "
                             "not a device readback"},
    ("paddle_tpu/hapi/model.py", "_CompiledStepper.train_step",
     "_host_bool"):
        {"max": 1, "reason": "grad-accumulation path: per-microbatch "
                             "verdict read keeps poisoned microbatches "
                             "out of the running sum"},
    ("paddle_tpu/hapi/model.py", "Model.train_batch", "item"):
        {"max": 1, "reason": "eager debug path only (prepare(jit=False))"},
    ("paddle_tpu/hapi/model.py", "Model.eval_batch", "item"):
        {"max": 1, "reason": "eval path: loss scalar for logs"},
    ("paddle_tpu/hapi/model.py", "Model.predict_batch", "asarray"):
        {"max": 1, "reason": "prediction sink: outputs leave the device "
                             "here by contract"},
    ("paddle_tpu/hapi/model.py", "Model.predict_batch", "numpy"):
        {"max": 1, "reason": "prediction sink (eager path): outputs "
                             "leave the device here by contract"},
    ("paddle_tpu/optimizer/optimizer.py", "Optimizer.set_state_dict",
     "asarray"):
        {"max": 1, "reason": "checkpoint-restore path: host state_dict "
                             "values are ingested (H2D), never per-step"},
    # serving engine: the one-host-sync-per-chunk contract — the chunk
    # boundary reads back ONE bundled device_get (prefill first-tokens +
    # chunk tokens + slot liveness); everything else stays on device
    ("paddle_tpu/inference/serving.py", "ServingEngine._sync",
     "device_get"):
        {"max": 1, "reason": "THE chunk-boundary readback: one bundled "
                             "device_get per decode chunk streams tokens "
                             "and frees slots — never per token"},
    ("paddle_tpu/inference/serving.py", "ServingEngine.submit",
     "asarray"):
        {"max": 1, "reason": "H2D ingest of the request prompt (host "
                             "list/array -> int32), not a readback"},
    ("paddle_tpu/inference/serving.py", "ServingEngine._resume_prompt",
     "asarray"):
        {"max": 1, "reason": "admission-time resume-prompt assembly "
                             "(host token list -> int32), not a "
                             "readback"},
    # paged-KV manager (inference/kvcache.py): admission-time syncs only
    ("paddle_tpu/inference/kvcache.py", "PagedKVManager.plan",
     "asarray"):
        {"max": 1, "reason": "admission-time prompt ingest for prefix "
                             "keying/page planning (host array "
                             "canonicalization), not a readback"},
    ("paddle_tpu/inference/kvcache.py", "prefix_affinity_key",
     "asarray"):
        {"max": 1, "reason": "routing-time prompt canonicalization for "
                             "the fleet affinity key (host array), not "
                             "a readback"},
    ("paddle_tpu/inference/kvcache.py", "PagedKVManager.export_pages",
     "device_get"):
        {"max": 1, "reason": "disaggregation seam: the prefill->decode "
                             "KV-page handoff is D2H by design and off "
                             "the chunk hot path (one bundled readback "
                             "per export)"},
    ("paddle_tpu/inference/kvcache.py", "PagedKVManager.export_pages",
     "asarray"):
        {"max": 1, "reason": "disaggregation seam: host-side page-index "
                             "assembly for the export gather, not a "
                             "readback"},
    ("paddle_tpu/inference/kvcache.py", "PagedKVManager.import_pages",
     "asarray"):
        {"max": 2, "reason": "disaggregation seam: H2D ingest of the "
                             "imported page payload + its index vector, "
                             "not a readback"},
    # fleet router (inference/router.py): H2D ingest only
    ("paddle_tpu/inference/router.py", "ServingFleet.submit", "asarray"):
        {"max": 1, "reason": "H2D ingest of the request prompt (host "
                             "list/array -> int32), not a readback"},
    # speculative decoding (inference/speculative.py): H2D ingest only
    ("paddle_tpu/inference/speculative.py", "speculative_generate",
     "asarray"):
        {"max": 1, "reason": "H2D ingest of the prompt ids (host "
                             "list/array -> int32), not a readback"},
    # observability: the exporter-side sync funnel.  Recording is host-
    # only by contract; a device scalar handed to a gauge materializes
    # exactly once, at export time, through this one budgeted site
    # (the _host_bool pattern applied to telemetry).
    ("paddle_tpu/observability/export.py", "_materialize", "asarray"):
        {"max": 1, "reason": "exporter-side only: collapse a device "
                             "scalar to host at snapshot/exposition "
                             "time — never on the recording path"},
}

# -- tracer-safety (tracer_safety.py) --------------------------------------
#
# Jit surfaces that are nested functions (a decorator can't reach them):
# (relpath, AST qualname).  Keep in sync with the runtime
# register_jit_surface() calls in the same modules.
EXTRA_JIT_SURFACES = (
    ("paddle_tpu/models/generation.py", "generate.run"),
    ("paddle_tpu/models/generation.py", "generate.beam_run"),
    ("paddle_tpu/models/generation.py", "generate.prefill"),
    # apply/pick builders shared by generate() and the serving engine
    ("paddle_tpu/models/generation.py", "build_apply.apply"),
    ("paddle_tpu/models/generation.py", "build_pick.pick"),
    # serving engine: bucket prefill + chunked decode (inference/serving.py)
    ("paddle_tpu/inference/serving.py", "_build_prefill.prefill"),
    ("paddle_tpu/inference/serving.py", "_build_decode_chunk.decode_chunk"),
    # paged-KV serving: suffix prefill + paged chunked decode
    # (inference/kvcache.py; mirrors its register_jit_surface calls)
    ("paddle_tpu/inference/kvcache.py",
     "_build_paged_prefill.paged_prefill"),
    ("paddle_tpu/inference/kvcache.py",
     "_build_paged_decode_chunk.paged_decode_chunk"),
    # speculative decoding: drafters + compiled spec prefill/chunk +
    # the standalone entry's jitted body (inference/speculative.py;
    # mirrors its register_jit_surface calls)
    ("paddle_tpu/inference/speculative.py", "build_ngram_drafter.draft"),
    ("paddle_tpu/inference/speculative.py", "build_model_drafter.draft"),
    ("paddle_tpu/inference/speculative.py",
     "_build_spec_prefill.spec_prefill"),
    ("paddle_tpu/inference/speculative.py",
     "_build_spec_decode_chunk.spec_decode_chunk"),
    ("paddle_tpu/inference/speculative.py",
     "speculative_generate.spec_run"),
    # grad_comm: the traced bucketed-reduce closure the builder returns
    # + the quantized-wire reduce built with static world/chunk/mode
    ("paddle_tpu/distributed/grad_comm.py", "build_grad_reducer.reduce"),
    ("paddle_tpu/distributed/grad_comm.py",
     "_build_quant_reduce.quant_reduce"),
    # hybrid-parallel steppers (ISSUE 11 donation audit): both donate
    # their state trees — registered so the donation/tracer passes keep
    # them honest
    ("paddle_tpu/models/gpt_hybrid.py", "build_hybrid_gpt.step"),
    ("paddle_tpu/distributed/fleet/meta_parallel/pipeline_parallel.py",
     "_PipelineStepper._build.step"),
)

# -- donation (donation.py) ------------------------------------------------
#
# Parameter-name tokens that mark a jit-surface argument as a *large
# state tree* (params / optimizer state / KV pools / slot state):
# surfaces taking one must declare donate_argnums or pragma the jit
# line with the reason the tree must outlive the call.  Matched against
# the ``_``-split tokens of the parameter name, so ``train_vals`` and
# ``opt_state`` match but ``lr`` and ``key`` never do.
DONATABLE_PARAM_TOKENS = frozenset({
    "params", "pv", "pvals", "dpv", "dpvals", "state", "states",
    "caches", "cache", "kv", "dkv", "pool", "pools", "hist", "history",
    "buffer", "buffers", "vals", "tree", "trees", "slots", "weights",
    "opt",
})

# -- retrace-hazard (retrace_hazard.py) ------------------------------------
#
# The compile-surface vocabulary: every label passed to
# ``observability.compilestats.wrap`` (the ``pt_compile_*`` metrics'
# ``surface`` label set).  Retrace-hazard findings attribute to these
# same names so static findings and runtime ``compile_retrace`` events
# speak one language; tests cross-reference this tuple against the
# wrap() call sites in source (tests/test_graph_discipline.py).
COMPILE_SURFACES = (
    "hapi.train_step",
    "hapi.train_step_comm",
    "hapi.grad_step",
    "hapi.apply_step",
    "hapi.eval_step",
    "serving.prefill",
    "serving.decode_chunk",
    "serving.paged_prefill",
    "serving.paged_decode_chunk",
    "serving.spec_prefill",
    "serving.spec_decode_chunk",
    "speculative.generate",
    "generation.decode",
    # kernel registry (ops/registry.py *_SURFACE constants): standalone
    # dispatches of the fused kernels are compilestats-tracked under
    # these names so the roofline attributes per-kernel FLOPs/bytes;
    # traced calls inline into the enclosing stepper surface
    "kernel.flash_fwd",
    "kernel.flash_fwd_lse",
    "kernel.flash_bwd",
    "kernel.xent_fwd",
    "kernel.xent_bwd",
    "kernel.quant_matmul",
)

# Fallback surface labels for jit-cache sites whose module does not
# wrap with compilestats (the wrap string literal is the primary
# source): (relpath, enclosing function qualname) -> surface label.
SURFACE_LABELS = {}

# Parameter/local-name tokens that mark a value as *request data* (the
# extents that jitter per call): a cache-key component derived from a
# data value's ``len()``/``.shape`` is the unbucketed-retrace hazard.
RETRACE_DATA_TOKENS = frozenset({
    "input", "inputs", "ids", "prompt", "prompts", "tokens", "labels",
    "batch", "feed", "x", "y", "data",
})

# -- concurrency (concurrency.py) ------------------------------------------
#
# Modules whose host-side state crosses threads (dataloader producer
# threads, async checkpoint writers, the elastic heartbeat lease, the
# metrics registry, the serving scheduler/engine ahead of the
# multi-replica router).
CONCURRENCY_MODULES = (
    "paddle_tpu/inference/scheduler.py",
    "paddle_tpu/inference/serving.py",
    "paddle_tpu/inference/router.py",
    # prefill/decode handoff: record table + stats shared between the
    # router thread and prefill/decode workers
    "paddle_tpu/inference/handoff.py",
    "paddle_tpu/io/__init__.py",
    "paddle_tpu/io/worker.py",
    "paddle_tpu/distributed/checkpoint/__init__.py",
    "paddle_tpu/distributed/fleet/elastic/__init__.py",
    "paddle_tpu/observability/metrics.py",
    # flight recorder: hot threads record() while the daemon dump
    # worker drains forensic-bundle jobs
    "paddle_tpu/observability/flight.py",
)

# Classes (or "<module>" namespaces) whose public API is a declared
# cross-thread surface even when no Thread() appears in the file.
# ``entries`` lists the methods other threads may call concurrently
# with the owner loop ("*" = every public method is its own root).
CONCURRENT_CLASSES = {
    # the serving admission queue: router threads submit() while the
    # engine loop admits/releases/requeues (ROADMAP: multi-replica
    # serving tier)
    ("paddle_tpu/inference/scheduler.py", "FCFSScheduler"):
        {"entries": ["submit", "enqueue", "steal_tail"],
         "reason": "router threads submit/enqueue/steal while the "
                   "engine loop admits/releases — the queue and "
                   "free-list are the cross-thread boundary"},
    ("paddle_tpu/inference/serving.py", "ServingEngine"):
        {"entries": ["submit", "submit_request"],
         "reason": "submit()/submit_request() are the engine's cross-"
                   "thread entries (client threads + the fleet router "
                   "dispatching while the replica worker steps); "
                   "everything else runs on the engine event loop"},
    # the fleet router: client threads submit() while the run() loop
    # dispatches and replica worker threads step engines / report
    # finishes — the fleet queue and stats are the cross-thread boundary
    ("paddle_tpu/inference/router.py", "ServingFleet"):
        {"entries": ["submit"],
         "reason": "client threads submit while the router loop "
                   "dispatches and replica workers report finishes; "
                   "all shared fleet state is behind self._lock"},
    # the prefill/decode handoff coordinator: the router thread
    # launches/pumps while prefill workers deliver captured bundles and
    # decode workers consume/arm/fail records at their admission gate —
    # the record table and stats live behind self._lock
    ("paddle_tpu/inference/handoff.py", "HandoffCoordinator"):
        {"entries": ["_captured", "consume", "import_failed", "armed"],
         "reason": "prefill workers deliver via the stub callback "
                   "(_captured) and decode workers consume/arm/fail "
                   "via the record's delegate methods, concurrent "
                   "with the router thread's launch/pump"},
    # the metrics registry records from every thread by contract
    ("paddle_tpu/observability/metrics.py", "<module>"):
        {"entries": "*", "reason": "recording API is process-wide"},
    ("paddle_tpu/observability/metrics.py", "_Metric"):
        {"entries": "*", "reason": "metric instances record from any "
                                   "thread"},
    ("paddle_tpu/observability/metrics.py", "Counter"):
        {"entries": "*", "reason": "see _Metric"},
    ("paddle_tpu/observability/metrics.py", "Gauge"):
        {"entries": "*", "reason": "see _Metric"},
    ("paddle_tpu/observability/metrics.py", "Histogram"):
        {"entries": "*", "reason": "see _Metric"},
    ("paddle_tpu/observability/metrics.py", "MetricsRegistry"):
        {"entries": "*", "reason": "registration races recording"},
    # the flight recorder records from every hot thread (fit loop,
    # replica workers, the router loop) while its daemon dump worker
    # writes bundles; window/jobs/dump bookkeeping live behind
    # self._lock
    ("paddle_tpu/observability/flight.py", "FlightRecorder"):
        {"entries": ["record"],
         "reason": "record() is the declared cross-thread entry "
                   "(every sync point on every hot thread); the dump "
                   "worker shares the window/job state behind "
                   "self._lock"},
}

# (relpath, "Owner.attr" | "<module>.name") -> reason the unguarded
# access is sound (single-writer publish, GIL-atomic slot write,
# happens-before via Thread.start()/join()).  The concurrency pass's
# equivalent of HOST_SYNC_ALLOWLIST: the diff review sees the
# justification, not a silent data race.
THREAD_SAFE_STATE = {
    # metrics: the lock-free recording fast path (PR 5 design): single
    # bounded deque ring + single-slot list cells, GIL-atomic ops only
    ("paddle_tpu/observability/metrics.py", "<module>._ENABLED"):
        "single-slot list write; readers tolerate either value (the "
        "enable/disable race drops or keeps one sample, never corrupts)",
    ("paddle_tpu/observability/metrics.py", "<module>._CAPTURE"):
        "single-slot capture flag, same tolerance as _ENABLED",
    ("paddle_tpu/observability/metrics.py", "<module>._CLOCK_PAIR"):
        "single-slot write at start_capture; readers see old or new "
        "pair atomically",
    ("paddle_tpu/observability/metrics.py", "<module>._SAMPLES"):
        "bounded collections.deque ring: append/clear are GIL-atomic "
        "by design — the lock-free recording path is the point",
    # checkpoint: write-once publish, synchronized by join()/is_alive()
    ("paddle_tpu/distributed/checkpoint/__init__.py",
     "AsyncSaveHandle.exception"):
        "write-once by the writer thread before it exits; readers "
        "observe it only after join()/is_alive() established "
        "happens-before",
    # elastic: published before the heartbeat thread starts
    ("paddle_tpu/distributed/fleet/elastic/__init__.py",
     "ElasticManager._node_id"):
        "written in start() before Thread.start() publishes it to the "
        "heartbeat loop; never rewritten while the thread lives",
    ("paddle_tpu/distributed/fleet/elastic/__init__.py",
     "ElasticManager._endpoint"):
        "written in start() before Thread.start(), same "
        "happens-before as _node_id",
    ("paddle_tpu/distributed/fleet/elastic/__init__.py",
     "ElasticManager._store"):
        "TCPStore.add() is a store RPC (server-side atomic), not a "
        "local container mutation; the client is internally "
        "synchronized (PR 1 retry envelope)",
    # dataloader: single-writer liveness flags polled by the collector
    ("paddle_tpu/io/worker.py", "_MultiProcessIterBase._stopping"):
        "single-writer bool publish (consumer -> collector poll); "
        "GIL-atomic, the collector tolerates observing it late",
}

# Call terminals that return *static* (trace-time) values even when
# applied to traced arrays — metadata, not data.  Taint stops here.
STATIC_FUNCS = frozenset({
    "issubdtype", "result_type", "promote_types", "can_cast", "finfo",
    "iinfo", "broadcast_shapes", "ndim", "isinstance", "hasattr",
    # jnp.dtype(x) builds a dtype OBJECT (metadata) — its itemsize &co
    # are trace-time constants even when x came off a traced array
    "dtype",
})
# Attribute reads that are static under tracing (`.at` is deliberately
# NOT here: `x.at[i].set(v)` carries x's taint)
STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})

# -- mesh-axes / spec-drift (mesh_axes.py, spec_drift.py) -------------------
#
# The mesh-axis vocabulary: every axis name the framework itself
# hardcodes — in `PartitionSpec` literals, `shard_map` specs,
# `jax.sharding.Mesh` constructions and collective `axis_name=`
# arguments — must come from this tuple.  User-facing mesh wrappers
# (`ProcessMesh(dim_names=...)`, `auto_mesh`) take arbitrary names and
# are deliberately out of scope: the vocabulary governs the GSPMD hot
# paths the framework owns, not what users call their axes.
# Canonical order mirrors fleet topology (`_AXIS_ORDER` +
# the expert axis the auto-parallel Engine adds).
MESH_AXES = ("data", "pipe", "sharding", "sep", "model", "expert")

# -- dtype-flow (dtype_flow.py) ---------------------------------------------
#
# Modules whose compiled hot paths are declared bf16-capable: a literal
# `.astype(jnp.float32)` upcast or a dtype-less `jnp.zeros`-family
# allocation (which silently materializes fp32) inside them must match
# a contract entry below or carry a pragma.  Jit-surface functions are
# additionally checked wherever they live (the host-sync scoping rule).
DTYPE_MONITORED_MODULES = (
    "paddle_tpu/models/generation.py",
    "paddle_tpu/models/gpt_hybrid.py",
    "paddle_tpu/models/llama.py",
    "paddle_tpu/inference/serving.py",
    "paddle_tpu/inference/kvcache.py",
    "paddle_tpu/inference/speculative.py",
    "paddle_tpu/distributed/grad_comm.py",
    "paddle_tpu/distributed/pipeline.py",
    "paddle_tpu/hapi/model.py",
    "paddle_tpu/framework/guardian.py",
)

# (relpath, function qualname) -> reason the fp32 upcast is *by
# contract* (numerics, not an accident).  The host-sync allowlist
# pattern applied to precision: the diff review sees the accumulator
# contract explicitly instead of a silent upcast eating the bf16 win.
FP32_CONTRACT_CASTS = {
    ("paddle_tpu/models/generation.py", "build_pick.pick"):
        "sampling contract: log-softmax + temperature math in fp32 "
        "(bf16 logprobs skew the categorical draw)",
    ("paddle_tpu/models/generation.py", "generate.beam_run"):
        "beam-search scores are fp32 log-probs by contract; bf16 "
        "accumulation reorders beams after ~100 steps",
    ("paddle_tpu/models/generation.py", "generate.beam_run.body"):
        "per-step log-softmax feeding the fp32 beam-score accumulator",
    ("paddle_tpu/models/gpt_hybrid.py", "build_hybrid_gpt.loss_fn"):
        "xent logits widen to fp32 before log-softmax — the one "
        "blessed upcast of the bf16 training recipe",
    ("paddle_tpu/models/llama.py", "_rope"):
        "rotary angles/products in fp32: bf16 sin/cos loses position "
        "resolution past ~4k context",
    ("paddle_tpu/inference/kvcache.py", "quantize_kv"):
        "absmax/scale math runs in fp32 before narrowing to int8 — "
        "quantizer internals, not a hot-path leak",
    ("paddle_tpu/inference/kvcache.py", "dequantize_kv"):
        "dequant is a widen-then-rescale by definition; result is "
        "cast back to the compute dtype by the caller-passed `dtype`",
    ("paddle_tpu/distributed/grad_comm.py",
     "_build_quant_reduce.quant_reduce"):
        "EQuARX partial sums dequantize to fp32 between the "
        "all_to_all and all_gather phases (accuracy contract)",
    ("paddle_tpu/hapi/model.py",
     "_CompiledStepper._build_train.step.loss_f"):
        "AMP O1/O2 restores bf16 forward outputs to fp32 before the "
        "loss — the mixed-precision master contract",
    ("paddle_tpu/hapi/model.py", "_fp8_apply"):
        "fp8 train pilot: delayed-scaling amax/scale math runs in "
        "fp32 before the fake-quant narrows (the quantizer-internals "
        "contract, like kvcache.quantize_kv)",
    ("paddle_tpu/hapi/model.py",
     "_CompiledStepper._build_train_comm.shard_step.loss_f"):
        "AMP O1/O2 restores bf16 forward outputs to fp32 before the "
        "loss — the mixed-precision master contract",
    ("paddle_tpu/framework/guardian.py", "attribute_nonfinite"):
        "post-mortem nonfinite attribution widens on host; not a "
        "compiled hot path",
}

# (relpath, function qualname) -> reason a narrow-dtype cast
# (int8/fp8) without scale handling in the same function is sound.
NARROW_CAST_CONTRACT = {
    ("paddle_tpu/distributed/grad_comm.py", "_to_narrow"):
        "input is pre-scaled by every caller (`x / scale`); the "
        "helper only rounds/clips onto the wire dtype",
    ("paddle_tpu/nn/quant/__init__.py", "_unpack_int4"):
        "nibble repack of already-quantized int4 weights; the scale "
        "is applied by the `weight_dequantize` caller",
}

# quantize/dequantize callee pairs that must stay balanced per module:
# a module calling one side without the other ships garbage (quantized
# values read as raw ints, or a dequant of never-quantized data).
KV_QUANT_PAIRS = (
    ("quantize_kv", "dequantize_kv"),
)

# EQuARX narrowing wrappers (distributed/grad_comm.py): every call
# site must see a widening `.astype(jnp.float32)` dequant in the same
# function — the wire value is useless until rescaled to fp32.
EQUARX_NARROW_CALLEES = frozenset({"_to_narrow"})

# -- collective-order (collective_order.py) --------------------------------

COLLECTIVE_CALLEES = frozenset({
    "all_reduce", "all_gather", "all_gather_into_tensor", "reduce_scatter",
    "alltoall", "alltoall_single", "broadcast", "scatter", "barrier",
    "reduce", "gather", "ppermute", "batch_isend_irecv",
    "psum", "pmin", "pmax", "pmean", "all_to_all", "psum_scatter",
    "sync_global_devices", "broadcast_one_to_all",
    # grad_comm reducer wrappers (distributed/grad_comm.py): each hides
    # one or more lax collectives, so the bucketed-stepper surfaces stay
    # walkable — a rank-conditional call to the wrapper is exactly as
    # deadlock-prone as one to the raw collective it wraps
    "quant_reduce", "_psum_reduce", "_bf16_reduce", "reduce_vec",
    "reducer",
})

# Names whose value differs per rank: a branch on one of these around a
# collective is the classic SPMD deadlock.  (process_count / world_size
# are uniform across ranks and deliberately absent.)
RANK_NAMES = frozenset({
    "rank", "local_rank", "rank_id", "trainer_id", "group_rank",
    "dp_rank", "mp_rank", "pp_rank", "stage_id", "worker_index",
})
RANK_FUNCS = frozenset({
    "get_rank", "axis_index", "process_index", "get_group_rank",
    "get_local_rank",
})
