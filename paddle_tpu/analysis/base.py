"""Shared infrastructure for the static-analysis passes: findings,
source indexing (modules, imports, functions, call resolution), and
inline-pragma suppression.

Everything here is pure-AST — no imports of the analyzed code — so the
code passes run on fixture snippets and broken trees alike.  Only the
registry lints (registry_lints.py) import the live framework.
"""
import ast
import os
import re

# `# lint: allow(tracer-safety)` / `# lint: allow(host-readback, ...)`
# on a finding's line suppresses it (by pass name or finding code)
_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


class Finding:
    """One lint finding.  ``key()`` is the baseline identity — it
    deliberately excludes the line number so unrelated edits above a
    baselined finding don't resurrect it; ``detail`` (a short stable
    token like the offending callee) disambiguates within a function."""

    __slots__ = ("pass_name", "path", "line", "qualname", "code",
                 "message", "detail")

    def __init__(self, pass_name, path, line, qualname, code, message,
                 detail=""):
        self.pass_name = pass_name
        self.path = path.replace(os.sep, "/")
        self.line = int(line)
        self.qualname = qualname or "<module>"
        self.code = code
        self.message = message
        self.detail = detail

    def key(self):
        return (f"{self.pass_name}:{self.path}:{self.qualname}:"
                f"{self.code}:{self.detail}")

    def sort_key(self):
        return (self.pass_name, self.path, self.line, self.code,
                self.qualname, self.detail, self.message)

    def to_dict(self):
        return {"pass": self.pass_name, "path": self.path,
                "line": self.line, "qualname": self.qualname,
                "code": self.code, "detail": self.detail,
                "message": self.message, "key": self.key()}

    def __repr__(self):
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.code}] "
                f"{self.qualname}: {self.message}")


class FuncInfo:
    __slots__ = ("qualname", "node", "class_name", "module", "is_surface")

    def __init__(self, qualname, node, class_name, module, is_surface):
        self.qualname = qualname
        self.node = node
        self.class_name = class_name
        self.module = module
        self.is_surface = is_surface


class ModuleInfo:
    """One parsed source file: its AST, import maps and function index."""

    def __init__(self, path, relpath, modname, is_package, source, tree):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.modname = modname
        self.is_package = is_package
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.import_alias = {}   # local name -> dotted module
        self.from_imports = {}   # local name -> (dotted module, name)
        self.funcs = {}          # qualname -> FuncInfo
        self._index()

    # -- pragma suppression ------------------------------------------------
    def allowed_on_line(self, line):
        """Set of pass names / codes suppressed by a pragma on ``line``."""
        if 1 <= line <= len(self.lines):
            m = _PRAGMA_RE.search(self.lines[line - 1])
            if m:
                return {t.strip() for t in m.group(1).split(",") if t.strip()}
        return set()

    # -- indexing ----------------------------------------------------------
    def _resolve_relative(self, level, module):
        """Dotted target of a ``from <dots><module> import ...``."""
        if level == 0:
            return module or ""
        parts = self.modname.split(".")
        # a package's own module path counts as its first parent level
        if not self.is_package:
            parts = parts[:-1]
        parts = parts[:len(parts) - (level - 1)] if level > 1 else parts
        base = ".".join(parts)
        if module:
            return f"{base}.{module}" if base else module
        return base

    def _index(self):
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.scope = []        # (kind, name) stack
                self.class_stack = []

            def visit_Import(self, node):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    mod.import_alias[local] = target

            def visit_ImportFrom(self, node):
                base = mod._resolve_relative(node.level, node.module)
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    mod.from_imports[local] = (base, a.name)

            def _func(self, node):
                qual = ".".join([n for _, n in self.scope] + [node.name])
                cls = self.class_stack[-1] if self.class_stack else None
                surface = any(_decorator_is_surface(d)
                              for d in node.decorator_list)
                mod.funcs[qual] = FuncInfo(qual, node, cls, mod, surface)
                self.scope.append(("func", node.name))
                self.generic_visit(node)
                self.scope.pop()

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func

            def visit_ClassDef(self, node):
                self.scope.append(("class", node.name))
                self.class_stack.append(node.name)
                self.generic_visit(node)
                self.class_stack.pop()
                self.scope.pop()

        V().visit(self.tree)

    def alias_module(self, name):
        """Dotted module a local name refers to, or None."""
        if name in self.import_alias:
            return self.import_alias[name]
        fi = self.from_imports.get(name)
        if fi is not None:
            base, sub = fi
            return f"{base}.{sub}" if base else sub
        return None


def _decorator_is_surface(dec):
    d = dec
    if isinstance(d, ast.Call):
        d = d.func
    if isinstance(d, ast.Name):
        return d.id == "jit_surface"
    if isinstance(d, ast.Attribute):
        return d.attr == "jit_surface"
    return False


# telemetry wrappers a jit call may hide behind (compilestats.wrap and
# the hapi/serving aliases) — shared by the donation and retrace passes
WRAP_CALLEES = ("wrap", "_tracked", "_wrap")


def is_jax_jit_call(call, mod):
    """True for ``jax.jit(...)`` / ``jit(...)`` calls, resolved through
    the module's import aliases (incl. ``from jax import jit``)."""
    name = dotted(call.func)
    if not name:
        return False
    if name == "jit" or name.endswith(".jit"):
        root = name.split(".", 1)[0]
        target = mod.alias_module(root) or root
        if target == "jax" or target.startswith("jax."):
            return True
        if name == "jit" and (mod.alias_module("jit") or "").startswith(
                "jax"):
            return True
    return False


def assign_names(target):
    """Names bound by an assignment target (tuples/lists/starred
    unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from assign_names(e)
    elif isinstance(target, ast.Starred):
        yield from assign_names(target.value)


def int_literals(expr):
    """Statically-literal ints in a tuple/list/single expression —
    the donate_argnums / static_argnums shapes."""
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) \
        else [expr]
    return [e.value for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)]


def param_names(fnode):
    """Parameter names of a function node (vararg/kwarg included,
    ``self``/``cls`` excluded)."""
    a = fnode.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def enclosing_qualname(mod, node, default="<module>"):
    """Qualname of the innermost function containing ``node``."""
    best, best_span = default, None
    for qual, fi in mod.funcs.items():
        f = fi.node
        end = getattr(f, "end_lineno", f.lineno)
        if f.lineno <= node.lineno <= end:
            span = end - f.lineno
            if best_span is None or span < best_span:
                best, best_span = qual, span
    return best


def dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_terminal(func_expr):
    """Terminal name of a call target ('all_reduce' for
    dist.all_reduce), or None for dynamic targets."""
    if isinstance(func_expr, ast.Attribute):
        return func_expr.attr
    if isinstance(func_expr, ast.Name):
        return func_expr.id
    return None


# Parsed-module cache shared across every ProjectIndex in the process
# (one sweep runs 12 passes over one index, but ci_check/pytest build
# many contexts): keyed by (abspath, relpath) and invalidated on
# mtime/size change, so edits between runs are always re-parsed.
# ModuleInfo is immutable after construction — passes only read it.
_MODULE_CACHE = {}
_MODULE_CACHE_MAX = 4096

# Same idea for the registry/drift passes' reference files (tests/docs
# are re-read by several passes per sweep).
_TEXT_CACHE = {}


def read_text(path):
    """Read a reference text file through the mtime-keyed cache."""
    ap = os.path.abspath(path)
    st = os.stat(ap)
    stamp = (st.st_mtime_ns, st.st_size)
    hit = _TEXT_CACHE.get(ap)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    with open(ap, encoding="utf-8") as f:
        text = f.read()
    if len(_TEXT_CACHE) >= _MODULE_CACHE_MAX:
        _TEXT_CACHE.clear()
    _TEXT_CACHE[ap] = (stamp, text)
    return text


class ProjectIndex:
    """All scanned modules plus cross-module call resolution."""

    def __init__(self, root, files):
        self.root = os.path.abspath(root)
        self.modules = {}      # dotted modname -> ModuleInfo
        self.by_relpath = {}   # relpath -> ModuleInfo
        self.errors = []       # (relpath, message) parse failures
        for path in sorted(files):
            self._load(path)

    def _load(self, path):
        abspath = os.path.abspath(path)
        relpath = os.path.relpath(abspath, self.root)
        try:
            st = os.stat(abspath)
            stamp = (st.st_mtime_ns, st.st_size)
            hit = _MODULE_CACHE.get((abspath, relpath))
            if hit is not None and hit[0] == stamp:
                mod = hit[1]
                self.modules[mod.modname] = mod
                self.by_relpath[mod.relpath] = mod
                return
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            self.errors.append((relpath.replace(os.sep, "/"), str(e)))
            return
        is_package = os.path.basename(path) == "__init__.py"
        mp = relpath[:-3] if relpath.endswith(".py") else relpath
        if is_package:
            mp = os.path.dirname(relpath)
        modname = mp.replace(os.sep, ".").replace("/", ".")
        mod = ModuleInfo(path, relpath, modname, is_package, source, tree)
        if len(_MODULE_CACHE) >= _MODULE_CACHE_MAX:
            _MODULE_CACHE.clear()
        _MODULE_CACHE[(abspath, relpath)] = (stamp, mod)
        self.modules[modname] = mod
        self.by_relpath[mod.relpath] = mod

    def iter_modules(self):
        for rel in sorted(self.by_relpath):
            yield self.by_relpath[rel]

    # -- call resolution ---------------------------------------------------
    def resolve_call(self, mod, caller_qualname, func_expr):
        """Best-effort static resolution of a call target to a FuncInfo
        in the scanned set.  Dynamic targets resolve to None (the walk
        stops there — deliberately conservative)."""
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            parts = caller_qualname.split(".") if caller_qualname else []
            for i in range(len(parts), -1, -1):
                cand = ".".join(parts[:i] + [name])
                fi = mod.funcs.get(cand)
                if fi is not None:
                    return fi
            target = mod.from_imports.get(name)
            if target is not None:
                tmod = self.modules.get(target[0])
                if tmod is not None:
                    return tmod.funcs.get(target[1])
            return None
        if isinstance(func_expr, ast.Attribute) and \
                isinstance(func_expr.value, ast.Name):
            base = func_expr.value.id
            if base in ("self", "cls"):
                caller = mod.funcs.get(caller_qualname)
                cls = caller.class_name if caller else None
                if cls:
                    return mod.funcs.get(f"{cls}.{func_expr.attr}")
                return None
            target_mod = mod.alias_module(base)
            if target_mod is not None:
                tmod = self.modules.get(target_mod)
                if tmod is not None:
                    return tmod.funcs.get(func_expr.attr)
        return None


_PRUNE_DIRS = frozenset({"__pycache__", ".git", "build"})


def _collect_files(paths, exts):
    """Expand files/directories into a sorted file list, one shared
    prune set for every pass (AST and registry alike)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = [d for d in dirnames if d not in _PRUNE_DIRS]
                for fn in sorted(files):
                    if fn.endswith(exts):
                        out.append(os.path.join(dirpath, fn))
        elif p.endswith(exts):
            out.append(p)
    return sorted(set(out))


def collect_py_files(paths):
    return _collect_files(paths, (".py",))


def collect_text_files(paths, exts=(".py", ".md")):
    return _collect_files(paths, tuple(exts))
