"""Bench trajectory regression gate (opt-in ``bench`` pass).

The driver commits one ``BENCH_r*.json`` per round; nothing so far
*diffs* them — a 20% tokens/sec drop or a serving config flipping
``valid: false`` only surfaces when a human reads the numbers.  This
pass compares the newest two bench artifacts and fails on:

- a tracked throughput/MFU metric dropping by more than the threshold
  (relative; ``PADDLE_BENCH_THRESHOLD`` env or ``--threshold``,
  default 5%);
- a validity regression: a config whose ``valid`` flag flips
  true -> false, or that newly reports ``skipped``/``error``.

Deliberately **opt-in** (``tools/lint.py --passes bench`` or
``python tools/bench_compare.py``): bench numbers move with machine
load, so the gate belongs in the bench workflow, not in every lint run.
Higher-is-better is assumed for every tracked metric below.
"""
import glob
import json
import os
import re

from .base import Finding

__all__ = ["BenchComparePass", "bench_files", "load_bench", "compare",
           "missing_memory_artifact", "MEMORY_ARTIFACT",
           "DEFAULT_THRESHOLD", "THRESHOLD_ENV"]

DEFAULT_THRESHOLD = 0.05
THRESHOLD_ENV = "PADDLE_BENCH_THRESHOLD"

# per-config numeric fields worth gating (higher is better)
_RATE_KEYS = ("tokens_per_sec", "images_per_sec",
              "decode_tokens_per_sec", "useful_tokens_per_sec",
              "engine_tokens_per_sec", "mfu", "active_mfu")

# configs whose MFU must be PRESENT in the newest artifact (ISSUE 15):
# these are the headline optimization targets — the pairwise diff only
# sees *transitions*, so a config that errored two rounds in a row (or
# was dropped from the sweep) would otherwise stop being gated at all.
REQUIRED_MFU_CONFIGS = ("gpt125m_s4096",)

# standalone bench artifacts outside the BENCH_r* trajectory whose
# presence (and config coverage) the pass requires (ISSUE 19): the
# quantized-hot-path bench commits once per change, so a deleted or
# errored artifact would silently un-gate the int8/fp8 decode and the
# fp8 train pilot.
REQUIRED_ARTIFACTS = {
    "BENCH_quant.json": ("serving_quant", "fp8_train"),
}

# the HBM ledger artifact bench.py writes next to roofline.json
# (ISSUE 20): any committed bench trajectory must carry it, with a
# static row for EVERY surface in the jit-surface registry — a surface
# dropped from the ledger is memory-blind exactly where the envelope
# check matters
MEMORY_ARTIFACT = "telemetry/memory.json"


def missing_memory_artifact(root):
    """(filename, surface-or-None, why) rows when committed bench
    artifacts lack a valid ``telemetry/memory.json`` companion.  No
    bench artifacts at all -> no requirement (nothing to accompany)."""
    have_bench = bool(bench_files(root)) or any(
        os.path.exists(os.path.join(root, f))
        for f in REQUIRED_ARTIFACTS)
    if not have_bench:
        return []
    path = os.path.join(root, MEMORY_ARTIFACT)
    if not os.path.exists(path):
        return [(MEMORY_ARTIFACT, None,
                 "memory.json must accompany committed BENCH_* "
                 "artifacts")]
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [(MEMORY_ARTIFACT, None, f"unreadable: {e}")]
    surfaces = doc.get("surfaces")
    if not isinstance(surfaces, dict) or not surfaces:
        return [(MEMORY_ARTIFACT, None,
                 "no per-surface static ledger rows")]
    out = []
    from .allowlist import COMPILE_SURFACES
    for name in COMPILE_SURFACES:
        if not isinstance(surfaces.get(name), dict):
            out.append((MEMORY_ARTIFACT, name,
                        "registry surface has no static row"))
    return out


def missing_required_artifacts(root):
    """(filename, config-or-None, why) rows for every required
    standalone artifact that is absent, unreadable, or missing one of
    its required configs."""
    out = []
    for fname, cfg_names in sorted(REQUIRED_ARTIFACTS.items()):
        path = os.path.join(root, fname)
        if not os.path.exists(path):
            out.append((fname, None, "required bench artifact missing"))
            continue
        try:
            rec = load_bench(path)
        except (OSError, ValueError) as e:
            out.append((fname, None, f"unreadable: {e}"))
            continue
        configs = (rec.get("extra") or {}).get("configs") or {}
        for name in cfg_names:
            cfg = configs.get(name)
            if not isinstance(cfg, dict) or "error" in cfg \
                    or cfg.get("skipped"):
                out.append((fname, name,
                            "required config missing/errored/skipped"))
    return out


def missing_required_mfu(new_rec):
    """REQUIRED_MFU_CONFIGS entries whose newest record lacks a numeric
    ``mfu`` (absent config, error/skip, or a non-numeric field)."""
    configs = (new_rec.get("extra") or {}).get("configs") or {}
    out = []
    for name in REQUIRED_MFU_CONFIGS:
        cfg = configs.get(name)
        mfu = cfg.get("mfu") if isinstance(cfg, dict) else None
        if not isinstance(mfu, (int, float)) or isinstance(mfu, bool):
            out.append(name)
    return out


def bench_files(root):
    """BENCH_r*.json under ``root``, oldest first (numeric round
    order, not lexicographic)."""
    def round_of(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                  key=round_of)


def load_bench(path):
    """One bench record: handles both the raw bench.py JSON line and
    the driver wrapper that nests it under ``parsed``."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("parsed", data)


def _flatten(rec):
    """{metric key: value} of everything the gate tracks."""
    out = {}
    if isinstance(rec.get("value"), (int, float)):
        out[rec.get("metric", "value")] = rec["value"]
    extra = rec.get("extra") or {}
    if isinstance(extra.get("mfu"), (int, float)):
        out["extra.mfu"] = extra["mfu"]
    for name, cfg in sorted((extra.get("configs") or {}).items()):
        if not isinstance(cfg, dict):
            continue
        for k in _RATE_KEYS:
            if isinstance(cfg.get(k), (int, float)):
                out[f"configs.{name}.{k}"] = cfg[k]
        if "valid" in cfg:
            out[f"configs.{name}.valid"] = bool(cfg["valid"])
        if "skipped" in cfg or "error" in cfg:
            out[f"configs.{name}.unavailable"] = True
    return out


def compare(old_rec, new_rec, threshold=None):
    """Diff two bench records; returns a list of row dicts (every
    tracked metric) with ``regressed`` set where the gate trips."""
    if threshold is None:
        threshold = float(os.environ.get(THRESHOLD_ENV,
                                         DEFAULT_THRESHOLD))
    old, new = _flatten(old_rec), _flatten(new_rec)

    def newly_unavailable(key):
        # "configs.<name>.<field>" whose config newly reports
        # skipped/error — that regression is flagged once on its
        # .unavailable row, not once per vanished numeric field
        parts = key.split(".")
        return len(parts) == 3 and parts[0] == "configs" and \
            f"configs.{parts[1]}.unavailable" in new

    rows = []
    for key in sorted(set(old) | set(new)):
        o, n = old.get(key), new.get(key)
        row = {"key": key, "old": o, "new": n, "delta": None,
               "regressed": False, "why": None}
        if key.endswith(".unavailable"):
            if n and not o:
                row.update(regressed=True,
                           why="config newly skipped/errored")
        elif o is not None and n is None:
            # a tracked metric (or whole config) vanished from the
            # newer artifact — exactly the silent-disappearance class
            # the gate exists for
            if not newly_unavailable(key):
                row.update(regressed=True,
                           why="disappeared from the newer artifact")
        elif key.endswith(".valid"):
            if o is True and n is False:
                row.update(regressed=True,
                           why="validity flipped true -> false")
        elif isinstance(o, (int, float)) and \
                not isinstance(o, bool) and isinstance(n, (int, float)):
            if o > 0:
                delta = (n - o) / o
                row["delta"] = round(delta, 4)
                if delta < -threshold:
                    row.update(regressed=True,
                               why=f"dropped {-delta:.1%} "
                                   f"(threshold {threshold:.0%})")
        rows.append(row)
    return rows


class BenchComparePass:
    """Opt-in lint pass: diff the repo's newest two BENCH_r*.json.
    Needs at least two committed rounds; fewer is a clean pass (there
    is no trajectory to regress yet)."""

    name = "bench"
    optional = True

    def run(self, ctx):
        art_findings = []
        for fname, cfg, why in missing_required_artifacts(ctx.root):
            key = f"configs.{cfg}" if cfg else "artifact"
            art_findings.append(Finding(
                self.name, fname, 1, "<bench>", "bench-coverage",
                f"{key}: {why} — the quantized hot paths are ungated",
                key))
        for fname, surface, why in missing_memory_artifact(ctx.root):
            key = f"surfaces.{surface}" if surface else "artifact"
            art_findings.append(Finding(
                self.name, fname, 1, "<bench>", "bench-coverage",
                f"{key}: {why} — the HBM ledger is blind there", key))
        files = bench_files(ctx.root)
        if not files:
            return sorted(art_findings, key=Finding.sort_key)
        rel = os.path.relpath(files[-1], ctx.root).replace(os.sep, "/")
        try:
            new_rec = load_bench(files[-1])
        except (OSError, ValueError) as e:
            return art_findings + [
                Finding(self.name, rel, 1, "<bench>", "bench-unreadable",
                        f"cannot read bench artifact: {e}", "parse")]
        findings = art_findings
        # presence gate: required-MFU configs must carry a number in the
        # NEWEST artifact regardless of what older rounds reported
        for name in missing_required_mfu(new_rec):
            findings.append(Finding(
                self.name, rel, 1, "<bench>", "bench-coverage",
                f"configs.{name}.mfu: required config has no numeric "
                "MFU in the newest artifact (missing, errored or "
                "skipped) — the long-context target is ungated",
                f"configs.{name}.mfu"))
        if len(files) < 2:
            return sorted(findings, key=Finding.sort_key)
        old_p = files[-2]
        try:
            rows = compare(load_bench(old_p), new_rec)
        except (OSError, ValueError) as e:
            return findings + [Finding(self.name, rel, 1, "<bench>",
                                       "bench-unreadable",
                                       f"cannot diff bench artifacts: {e}",
                                       "parse")]
        for row in rows:
            if not row["regressed"]:
                continue
            findings.append(Finding(
                self.name, rel, 1, "<bench>", "bench-regression",
                f"{row['key']}: {row['old']} -> {row['new']} "
                f"({row['why']}) vs {os.path.basename(old_p)}",
                row["key"]))
        return sorted(findings, key=Finding.sort_key)
