"""Collective-order / deadlock pass: flag the SPMD deadlock shapes.

XLA collectives are matched by static program order — every rank must
issue the same collectives in the same order.  Two shapes break that:

1. A collective under a *rank-dependent* (or data-dependent) branch:
   ``if rank == 0: barrier()`` hangs every other rank forever.  The
   classic fleet-killer; PR 2's watchdog turns the hang into a timeout,
   this pass catches it before it ships.
2. ``if``/``else`` arms that both issue collectives but in *different
   static order*: rank A takes the then-arm (all_reduce, barrier), rank
   B the else-arm (barrier, all_reduce) — each blocks in a different
   collective and the fleet deadlocks.

Heuristics are syntactic: a condition is rank-dependent if it mentions a
rank-ish name (``rank``, ``local_rank``, ...) or call (``get_rank``,
``axis_index``, ...); data-dependent if it calls into jnp/jax/lax (a
traced verdict).  Uniform conditions (``process_count``, ``world_size``)
are deliberately not flagged — every rank agrees on them.
"""
import ast

from .base import Finding, call_terminal, dotted
from .allowlist import COLLECTIVE_CALLEES, RANK_NAMES, RANK_FUNCS

PASS_NAME = "collective-order"

# host metadata every rank agrees on — a branch on these is uniform, not
# data-dependent (e.g. `if jax.process_count() > 1: sync_global_devices()`
# is the standard single-host fast path, not a deadlock)
UNIFORM_FUNCS = frozenset({
    "process_count", "device_count", "local_device_count",
    "get_world_size", "world_size", "is_initialized",
})


def _collective_name(call):
    term = call_terminal(call.func)
    if term in COLLECTIVE_CALLEES:
        return term
    return None


def _is_rankish(name):
    return name in RANK_NAMES or name.split("_")[-1] == "rank"


def _cond_kind(test, mod):
    """'rank' / 'data' / None for a branch condition."""
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and _is_rankish(n.id):
            return "rank"
        if isinstance(n, ast.Attribute) and _is_rankish(n.attr):
            return "rank"
        if isinstance(n, ast.Call):
            term = call_terminal(n.func)
            if term in RANK_FUNCS:
                return "rank"
            if term in UNIFORM_FUNCS:
                continue
            name = dotted(n.func)
            if name:
                root = name.split(".", 1)[0]
                target = mod.alias_module(root) or root
                if target == "jax" or target.startswith("jax."):
                    return "data"
    return None


def _collectives_in(nodes):
    """Ordered collective-call names under ``nodes`` (no descent into
    nested defs — they execute on their own schedule)."""
    out = []
    stack = list(reversed(nodes))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.Call):
            c = _collective_name(n)
            if c is not None:
                out.append((c, n))
        stack.extend(reversed(list(ast.iter_child_nodes(n))))
    return out


class CollectiveOrderPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            self._scan(mod, findings)
        return sorted(findings, key=Finding.sort_key)

    def _scan(self, mod, findings):
        def flag(node, code, qual, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(
                self.name, mod.relpath, node.lineno, qual, code, message,
                detail))

        # each branch statement is visited under exactly one owner: the
        # innermost enclosing function (or <module>) — nested defs are
        # skipped in the owner's walk and visited as their own unit
        units = [("<module>", mod.tree.body)]
        units += [(qual, mod.funcs[qual].node.body)
                  for qual in sorted(mod.funcs)]
        for qual, body in units:
            stack = list(body)
            branch_nodes = []
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(n, (ast.If, ast.While)):
                    branch_nodes.append(n)
                stack.extend(ast.iter_child_nodes(n))
            branch_nodes.sort(key=lambda n: (n.lineno, n.col_offset))
            # one conditional-collective finding per call site: nested
            # kind-bearing branches must not re-report a call already
            # attributed to the outermost condition
            flagged_calls = set()
            # elif continuations: their chain is compared where it roots
            elif_children = {id(b.orelse[0]) for b in branch_nodes
                            if isinstance(b, ast.If) and
                            len(b.orelse) == 1 and
                            isinstance(b.orelse[0], ast.If)}
            for n in branch_nodes:
                kind = _cond_kind(n.test, mod)
                if kind is not None:
                    # an `elif` whose own condition is kind-bearing
                    # reports its collectives itself (with the RIGHT
                    # test text) when that nested If is visited — don't
                    # double-report them under the outer condition.  An
                    # elif with a neutral condition stays attributed to
                    # the outer one (reaching it depends on it).
                    orelse = n.orelse
                    if len(orelse) == 1 and isinstance(orelse[0], ast.If) \
                            and _cond_kind(orelse[0].test, mod) is not None:
                        orelse = []
                    for cname, cnode in _collectives_in(n.body) + \
                            _collectives_in(orelse):
                        if id(cnode) in flagged_calls:
                            continue
                        flagged_calls.add(id(cnode))
                        flag(cnode, f"{kind}-conditional-collective", qual,
                             f"collective `{cname}` under a "
                             f"{kind}-dependent branch "
                             f"(`{ast.unparse(n.test)[:60]}`) — ranks "
                             "that skip the branch never enter the "
                             "collective and the fleet deadlocks; hoist "
                             "it out of the branch or make the condition "
                             "uniform across ranks",
                             f"{cname}:{ast.unparse(n.test)[:40]}")
                if isinstance(n, ast.If) and n.orelse and \
                        id(n) not in elif_children:
                    # divergence across the WHOLE if/elif/else chain,
                    # compared once where the chain roots.  Restricted
                    # to all-neutral conditions: kind-bearing arms were
                    # already flagged individually above, and flagging
                    # their order too would double-report one defect.
                    arms, conds, cur = [], [], n
                    while True:
                        arms.append(cur.body)
                        conds.append(cur.test)
                        if len(cur.orelse) == 1 and \
                                isinstance(cur.orelse[0], ast.If):
                            cur = cur.orelse[0]
                            continue
                        if cur.orelse:
                            arms.append(cur.orelse)
                        break
                    if any(_cond_kind(c, mod) is not None for c in conds):
                        continue
                    seqs = [[c for c, _ in _collectives_in(a)]
                            for a in arms]
                    nonempty = [s for s in seqs if s]
                    if len(nonempty) >= 2 and \
                            any(s != nonempty[0] for s in nonempty):
                        flag(n, "divergent-collective-order", qual,
                             "branch arms issue different collective "
                             f"sequences ({nonempty}) — if ranks can "
                             "disagree on the condition each blocks in "
                             "a different collective (SPMD deadlock); "
                             "restructure so every arm issues the same "
                             "sequence",
                             "|".join("+".join(s) for s in nonempty))
