"""Host-concurrency pass: shared host state mutated from more than one
thread entry point must be lock-guarded or explicitly thread-confined.

The compiled hot paths are single-dispatcher by construction, but the
*host* side is not: dataloader producer threads, ``AsyncSaveHandle``
writers, the elastic heartbeat, the metrics registry, and — ahead of
the multi-replica serving router — ``ServingEngine.submit`` /
``FCFSScheduler`` all touch instance or module state from more than one
thread.  This pass inventories those mutations statically and requires
each one to be either inside a ``with <...lock>:`` block, covered by a
``THREAD_SAFE_STATE`` allowlist entry (with the reason the lock-free
access is sound), or pragma'd.

Scope: modules listed in ``allowlist.CONCURRENCY_MODULES``.  Thread
entry points are found syntactically (``threading.Thread(target=...)``,
``atexit.register(...)``) and declared via
``allowlist.CONCURRENT_CLASSES`` for classes (or a module namespace,
``"<module>"``) whose *public API* is the cross-thread surface: the
scheduler's ``submit`` may be called from router threads while the
engine loop admits/releases — no ``Thread`` appears in the file, but
the contract is concurrent.

Sharedness is computed per *cell*: a plain attribute is one cell;
dict-style subscript accesses with constant keys are per-key cells
(``self.stats["requests"]`` from ``submit`` does not conflict with the
engine loop's ``self.stats["chunks"]`` under the GIL — but the same key
from two roots does; a non-constant key conflicts with every key).  A
cell is shared when it is accessed from two or more roots and mutated
by at least one of them.  Constructor bodies are exempt — the object is
not shared yet — but a def *nested* in a constructor and handed to
``Thread(target=...)`` is not (it runs later, on its own thread).

Codes:

- ``unguarded-shared-mutation`` — mutation of a shared cell outside a
  lock.
- ``check-then-act`` — an ``if``/``while`` tests a shared cell and its
  body mutates that same cell, with the test outside the lock: the
  classic TOCTOU on a queue/free-list (``if not self._free: ...
  self._free.pop()``).
"""
import ast

from .base import Finding, call_terminal, dotted
from .allowlist import (CONCURRENCY_MODULES, CONCURRENT_CLASSES,
                        THREAD_SAFE_STATE)

PASS_NAME = "concurrency"

# attribute-call terminals that mutate their receiver in place; queue
# ops (put/push/get on queue.Queue) and Event.set/clear are
# deliberately absent — thread-safe by design
_MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard",
    "update", "add", "setdefault", "sort", "reverse",
})
_LOCKY_FRAGMENTS = ("lock", "cond", "_cv", "mutex")


def _is_locky(expr):
    name = dotted(expr) or ""
    leaf = name.rsplit(".", 1)[-1].lower()
    return any(f in leaf for f in _LOCKY_FRAGMENTS)


def _with_locked(with_node, outer_locked):
    """Lock state inside a ``with`` body — THE single place that
    decides what counts as taking a lock (both walkers route here)."""
    return outer_locked or any(_is_locky(i.context_expr)
                               for i in with_node.items)


def _walk_lockstate(body, locked=False):
    """Full-descent (node, locked) walk of a statement list: nested
    defs/classes are skipped, ``with`` bodies carry their lock state."""
    stack = [(n, locked) for n in reversed(body)]
    while stack:
        n, lk = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.With):
            inner = _with_locked(n, lk)
            for c in reversed(n.body):
                stack.append((c, inner))
            for i in n.items:
                stack.append((i.context_expr, lk))
            continue
        yield n, lk
        for c in reversed(list(ast.iter_child_nodes(n))):
            stack.append((c, lk))


class _Access:
    __slots__ = ("cell", "node", "mutates", "locked", "func")

    def __init__(self, cell, node, mutates, locked, func):
        self.cell = cell           # (owner, attr, key)
        self.node = node
        self.mutates = mutates
        self.locked = locked
        self.func = func


def _cells_conflict(a, b):
    """Same owner+attr; per-key cells conflict only on equal (or
    unknown) keys."""
    if a[:2] != b[:2]:
        return False
    ka, kb = a[2], b[2]
    return ka is None or kb is None or ka == kb


def _iter_accesses(body, mod, module_containers, qual,
                   locked_init=False):
    """Yield _Access records for a statement list, without descending
    into nested defs (they are their own functions).  Subscript bases
    are consumed into per-key cells, never double-counted as bare
    attribute reads."""

    def cell_for(base, key):
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self":
            return ("self", base.attr, key)
        if isinstance(base, ast.Name) and base.id in module_containers:
            return ("<module>", base.id, key)
        return None

    stack = [(n, locked_init) for n in reversed(body)]
    while stack:
        n, lk = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.With):
            inner = _with_locked(n, lk)
            for c in reversed(n.body):
                stack.append((c, inner))
            for i in n.items:
                stack.append((i.context_expr, lk))
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    key = repr(t.slice.value) \
                        if isinstance(t.slice, ast.Constant) else None
                    cell = cell_for(t.value, key)
                    if cell is not None:
                        yield _Access(cell, t, True, lk, qual)
                    stack.append((t.slice, lk))
                    continue
                cell = cell_for(t, None)
                if cell is not None:
                    yield _Access(cell, t, True, lk, qual)
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.append((t, lk))
            if n.value is not None:
                stack.append((n.value, lk))
            continue
        if isinstance(n, ast.Delete):
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    key = repr(t.slice.value) \
                        if isinstance(t.slice, ast.Constant) else None
                    cell = cell_for(t.value, key)
                else:
                    cell = cell_for(t, None)
                if cell is not None:
                    yield _Access(cell, t, True, lk, qual)
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            term = call_terminal(n.func)
            if term in _MUTATING_METHODS:
                recv = n.func.value
                key = None
                if isinstance(recv, ast.Subscript):
                    key = repr(recv.slice.value) \
                        if isinstance(recv.slice, ast.Constant) else None
                    recv = recv.value
                cell = cell_for(recv, key)
                if cell is not None:
                    yield _Access(cell, n, True, lk, qual)
                    for a in n.args + [kw.value for kw in n.keywords]:
                        stack.append((a, lk))
                    continue
            stack.append((n.func.value, lk))
            for a in n.args + [kw.value for kw in n.keywords]:
                stack.append((a, lk))
            continue
        if isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Load):
            key = repr(n.slice.value) \
                if isinstance(n.slice, ast.Constant) else None
            cell = cell_for(n.value, key)
            if cell is not None:
                yield _Access(cell, n, False, lk, qual)
                stack.append((n.slice, lk))
                continue
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            cell = cell_for(n, None)
            if cell is not None:
                yield _Access(cell, n, False, lk, qual)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            cell = cell_for(n, None)
            if cell is not None:
                yield _Access(cell, n, False, lk, qual)
        for c in reversed(list(ast.iter_child_nodes(n))):
            stack.append((c, lk))


def _module_containers(mod):
    """Module-level names bound to mutable containers —
    ``threading.local()`` is thread-confined by construction and
    exempt."""
    out = set()
    for n in mod.tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            v = n.value
            name = n.targets[0].id
            if isinstance(v, (ast.List, ast.Dict, ast.Set)):
                out.add(name)
            elif isinstance(v, ast.Call):
                leaf = (dotted(v.func) or "").rsplit(".", 1)[-1]
                if leaf in ("dict", "list", "set", "deque",
                            "defaultdict", "OrderedDict"):
                    out.add(name)
    return out


def _thread_targets(mod):
    """Qualnames handed to ``threading.Thread(target=...)`` /
    ``atexit.register(...)``: ``("method", attr)`` for ``self.m``
    targets, ``("local", encl_qual, name)`` for local/module
    functions."""
    out = []
    walk_units = [("<module>", mod.tree.body)]
    walk_units += [(q, mod.funcs[q].node.body) for q in sorted(mod.funcs)]
    for qual, body in walk_units:
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Call):
                cname = dotted(n.func) or ""
                leaf = cname.rsplit(".", 1)[-1]
                tgt = None
                if leaf == "Thread":
                    for kw in n.keywords:
                        if kw.arg == "target":
                            tgt = kw.value
                elif leaf == "register" and cname.startswith("atexit"):
                    tgt = n.args[0] if n.args else None
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    out.append(("method", tgt.attr))
                elif isinstance(tgt, ast.Name):
                    out.append(("local", qual, tgt.id))
            stack.extend(ast.iter_child_nodes(n))
    return out


def _reachable(roots, callgraph):
    out = set(roots)
    work = list(roots)
    while work:
        q = work.pop()
        for callee in callgraph.get(q, ()):
            if callee not in out:
                out.add(callee)
                work.append(callee)
    return out


class ConcurrencyPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            if not any(mod.relpath == m or mod.relpath.endswith("/" + m)
                       for m in CONCURRENCY_MODULES):
                continue
            self._scan(mod, findings)
        return sorted(findings, key=Finding.sort_key)

    def _scan(self, mod, findings):
        def flag(node, qual, code, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(self.name, mod.relpath, node.lineno,
                                    qual, code, message, detail))

        containers = _module_containers(mod)
        targets = _thread_targets(mod)

        # units: one per class, plus the module namespace (top-level
        # functions and their nested defs, which see module containers)
        units = {"<module>": {}}
        for qual, fi in mod.funcs.items():
            root = qual.split(".")[0]
            if root in mod.funcs or "." not in qual:
                units["<module>"][qual] = fi
            else:
                units.setdefault(root, {})[qual] = fi

        declared = {}
        for (rel, cls), meta in CONCURRENT_CLASSES.items():
            if mod.relpath == rel or mod.relpath.endswith("/" + rel):
                declared[cls] = meta

        for unit_name in sorted(units):
            self._scan_unit(mod, unit_name, units[unit_name], containers,
                            targets, declared.get(unit_name), flag)

    def _scan_unit(self, mod, unit_name, funcs, containers, targets,
                   decl, flag):
        if not funcs:
            return
        is_module_unit = unit_name == "<module>"

        callgraph = {}
        for qual, fi in funcs.items():
            edges = set()
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self" and not is_module_unit:
                    cand = f"{unit_name}.{n.func.attr}"
                    if cand in funcs:
                        edges.add(cand)
                elif isinstance(n.func, ast.Name):
                    parts = qual.split(".")
                    for i in range(len(parts), -1, -1):
                        cand = ".".join(parts[:i] + [n.func.id])
                        if cand in funcs:
                            edges.add(cand)
                            break
            callgraph[qual] = edges

        entry_roots = {}
        for tgt in targets:
            if tgt[0] == "method" and not is_module_unit:
                cand = f"{unit_name}.{tgt[1]}"
                if cand in funcs:
                    entry_roots[f"thread:{cand}"] = cand
            elif tgt[0] == "local":
                _, encl_qual, local = tgt
                for cand in (f"{encl_qual}.{local}", local):
                    if cand in funcs:
                        entry_roots[f"thread:{cand}"] = cand
                        break
        if decl:
            entries = decl.get("entries", "*")
            quals = []
            if entries == "*":
                quals = [q for q in funcs
                         if not q.rsplit(".", 1)[-1].startswith("_")
                         and q.count(".") == (0 if is_module_unit else 1)]
            else:
                for e in entries:
                    cand = e if is_module_unit else f"{unit_name}.{e}"
                    if cand in funcs:
                        quals.append(cand)
            for q in quals:
                entry_roots[f"api:{q.rsplit('.', 1)[-1]}"] = q
        if not entry_roots:
            return

        entry_reach = {r: _reachable({q}, callgraph)
                       for r, q in entry_roots.items()}
        entry_starts = set(entry_roots.values())
        # the owner thread enters through the unit's PUBLIC api (plus
        # dunders like __next__); a private helper only called from a
        # thread entry (or only from the constructor) must not inherit
        # a phantom owner root
        def _owner_entry(qual):
            leaf = qual.rsplit(".", 1)[-1]
            if leaf in ("__init__", "__new__", "__del__"):
                return False
            return not leaf.startswith("_") or (
                leaf.startswith("__") and leaf.endswith("__"))
        owner_start = {q for q in funcs
                       if q not in entry_starts and _owner_entry(q)}
        owner_reach = _reachable(owner_start, callgraph)

        def is_ctor(qual):
            return qual.rsplit(".", 1)[-1] in ("__init__", "__new__",
                                               "__del__")

        accesses = []                    # (root, _Access)
        for qual, fi in funcs.items():
            if is_ctor(qual):
                continue
            roots_here = [r for r, reach in entry_reach.items()
                          if qual in reach]
            if qual in owner_reach or not roots_here:
                roots_here.append("owner")
            for acc in _iter_accesses(fi.node.body, mod, containers,
                                      qual):
                for r in roots_here:
                    accesses.append((r, acc))

        mutated_cells = {a.cell for _, a in accesses if a.mutates}
        shared = set()
        for cell in mutated_cells:
            touching = [(r, a) for r, a in accesses
                        if _cells_conflict(a.cell, cell)]
            roots = {r for r, _ in touching}
            if len(roots) >= 2 and any(r != "owner" for r in roots):
                shared.add(cell)

        seen = set()
        for root, acc in accesses:
            if not acc.mutates or acc.locked:
                continue
            # flag only mutations whose OWN cell is shared: the engine
            # loop's stats["chunks"] does not become hot because
            # submit() touches stats["requests"]
            if acc.cell not in shared:
                continue
            if self._allowlisted(mod, unit_name, acc.cell):
                continue
            ident = (id(acc.node), acc.cell)
            if ident in seen:
                continue
            seen.add(ident)
            roots = sorted({r for r, a in accesses
                            if _cells_conflict(a.cell, acc.cell)})
            flag(acc.node, acc.func, "unguarded-shared-mutation",
                 f"`{self._cellname(unit_name, acc.cell)}` is reached "
                 f"from multiple thread roots ({', '.join(roots)}) but "
                 "this mutation is not lock-guarded — wrap the mutation "
                 "in `with self._lock:` (or a module lock), or add a "
                 "THREAD_SAFE_STATE entry in "
                 "paddle_tpu/analysis/allowlist.py stating why the "
                 "lock-free access is sound",
                 self._cellname(unit_name, acc.cell))

        for qual, fi in funcs.items():
            if is_ctor(qual):
                continue
            self._check_then_act(mod, unit_name, qual, fi, containers,
                                 shared, flag)

    def _check_then_act(self, mod, unit_name, qual, fi, containers,
                        shared, flag):
        def accs(nodes, locked=False):
            return list(_iter_accesses(nodes, mod, containers, qual,
                                       locked_init=locked))

        for n, lk in _walk_lockstate(fi.node.body):
            if lk or not isinstance(n, (ast.If, ast.While)):
                continue
            test_cells = {a.cell
                          for a in accs([ast.Expr(value=n.test)])
                          if any(_cells_conflict(a.cell, s)
                                 for s in shared)}
            if not test_cells:
                continue
            # a mutation under its OWN lock does not absolve the
            # unlocked test: check-outside/act-inside is still the
            # TOCTOU (two threads pass the check, the second act
            # corrupts) — the lock must span the whole region
            hits = [a for a in accs(n.body)
                    if a.mutates and
                    any(_cells_conflict(a.cell, c) for c in test_cells)]
            hits = [a for a in hits
                    if not self._allowlisted(mod, unit_name, a.cell)]
            if not hits:
                continue
            if {self.name, "check-then-act"} & \
                    mod.allowed_on_line(n.lineno):
                continue
            flag(n, qual, "check-then-act",
                 f"test reads shared "
                 f"`{self._cellname(unit_name, hits[0].cell)}` and the "
                 "body mutates it outside a lock — another thread can "
                 "change the state between check and act (TOCTOU on a "
                 "queue/free-list); take the lock around the whole "
                 "check-then-act region",
                 self._cellname(unit_name, hits[0].cell))

    @staticmethod
    def _cellname(unit_name, cell):
        owner, attr, key = cell
        base = f"{unit_name}.{attr}" if owner == "self" \
            else f"<module>.{attr}"
        return base + (f"[{key}]" if key else "")

    @staticmethod
    def _allowlisted(mod, unit_name, cell):
        owner, attr, _key = cell
        name = f"{unit_name}.{attr}" if owner == "self" \
            else f"<module>.{attr}"
        for (rel, entry), _reason in THREAD_SAFE_STATE.items():
            if entry == name and (mod.relpath == rel or
                                  mod.relpath.endswith("/" + rel)):
                return True
        return False
