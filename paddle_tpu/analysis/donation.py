"""Donation/aliasing discipline pass: large state trees entering a
registered jit surface must be donated, and a donated buffer must never
be touched again.

Why a *pass*: XLA aliases a donated input buffer to an output, so an
un-donated params/opt-state/KV tree round-trips HBM on every hot
dispatch — double the working set, and exactly the class of invariant
PAPERS.md ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training") argues should be machine-checked, not
reviewed.  The flip side is sharper: after donation the old buffer is
*invalid* — reading it raises at runtime (if you are lucky), and
re-entering it into a second jit double-donates (the aliased-buffer
hazard documented at ``paddle_tpu/nn/layer/transformer.py``'s
``_reown_params``).

Four finding codes:

- ``missing-donation`` — a registered jit surface (``@jit_surface``
  builders and ``EXTRA_JIT_SURFACES`` nested defs) is ``jax.jit``-ed
  with arguments that carry large state trees (parameter-name
  heuristics: ``*_vals``/``pv``/``params``/``opt_state``/``caches``/
  ``pool``/``hist``/...) but no ``donate_argnums``/``donate_argnames``.
  Donate the consumed trees, or pragma the jit line with a one-line
  justification when the arguments must outlive the call (live weights,
  trip-path state).
- ``use-after-donate`` — the caller reads a variable it passed in a
  donated position after the call returns.
- ``double-donation`` — one variable passed into two donated positions
  of the same call (two aliased output buffers, one backing store).
- ``donated-reentry`` — a variable passed in a donated position of one
  jit call is later fed to a *second* jitted callable.

Mechanics are deliberately name-based and local (pure AST): the pass
tracks names bound to ``jax.jit(...)`` results in the same function —
including ``fn = cache[sig] = jax.jit(...)`` chains and
``compilestats.wrap(jax.jit(...), ...)`` wrappers — and follows
donated *Name* arguments through subsequent statements by line order.
Attribute-held jits and cross-function flows are out of scope (the
runtime invalidation error covers them); the pass exists to catch the
local patterns review keeps missing.
"""
import ast

from .base import (Finding, call_terminal, is_jax_jit_call, assign_names,
                   enclosing_qualname, int_literals, param_names,
                   WRAP_CALLEES)
from .allowlist import EXTRA_JIT_SURFACES, DONATABLE_PARAM_TOKENS

PASS_NAME = "donation"


def _unwrap_jit(expr, mod):
    """The ``jax.jit`` Call inside ``expr``, looking through telemetry
    wrappers (``compilestats.wrap(jax.jit(...), ...)``) and tuple
    containers; None if ``expr`` holds no jit call."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            if is_jax_jit_call(n, mod):
                return n
            if call_terminal(n.func) in WRAP_CALLEES:
                stack.extend(n.args)
                continue
        if isinstance(n, (ast.Tuple, ast.List)):
            stack.extend(n.elts)
    return None


def _donated_positions(jit_call):
    """Positions named by ``donate_argnums`` (ints when statically
    literal).  Returns (has_donation, positions)."""
    for kw in jit_call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return True, int_literals(kw.value)
    return False, []


def _jit_targets(jit_call, mod, enclosing_qual, index):
    """FuncInfos the jit call compiles: a Name (both arms of an IfExp),
    or the nested defs of a builder invoked inline
    (``jax.jit(_build_prefill(...))``)."""
    if not jit_call.args:
        return []
    arg = jit_call.args[0]
    names = []
    if isinstance(arg, ast.Name):
        names = [arg]
    elif isinstance(arg, ast.IfExp):
        names = [a for a in (arg.body, arg.orelse)
                 if isinstance(a, ast.Name)]
    out = []
    for nm in names:
        parts = enclosing_qual.split(".") if enclosing_qual else []
        for i in range(len(parts), -1, -1):
            cand = ".".join(parts[:i] + [nm.id])
            fi = mod.funcs.get(cand)
            if fi is not None:
                out.append(fi)
                break
    if isinstance(arg, ast.Call):
        builder = index.resolve_call(mod, enclosing_qual, arg.func)
        if builder is not None:
            prefix = builder.qualname + "."
            for qual in sorted(builder.module.funcs):
                if qual.startswith(prefix) and \
                        "." not in qual[len(prefix):]:
                    out.append(builder.module.funcs[qual])
    return out


def _surface_quals(mod):
    """Qualnames in ``mod`` that are registered surfaces (decorated or
    EXTRA)."""
    quals = {q for q, fi in mod.funcs.items() if fi.is_surface}
    for rel, qual in EXTRA_JIT_SURFACES:
        if mod.relpath == rel or mod.relpath.endswith("/" + rel):
            quals.add(qual)
    return quals


def _state_params(fnode):
    """Parameter names of ``fnode`` that look like large state trees."""
    return [n for n in param_names(fnode)
            if set(n.lower().split("_")) & DONATABLE_PARAM_TOKENS]


class DonationPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        self._squals = {}     # per-run cache: relpath -> surface quals
        for mod in ctx.index.iter_modules():
            self._scan_module(mod, ctx.index, findings)
        return sorted(findings, key=Finding.sort_key)

    def _surfaces_of(self, mod):
        if mod.relpath not in self._squals:
            self._squals[mod.relpath] = _surface_quals(mod)
        return self._squals[mod.relpath]

    def _scan_module(self, mod, index, findings):

        def flag(node, qual, code, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(self.name, mod.relpath, node.lineno,
                                    qual, code, message, detail))

        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and is_jax_jit_call(n, mod):
                self._check_jit_site(n, mod, index, flag)

        # caller-side flow checks run per function body
        for qual in sorted(mod.funcs):
            self._check_caller(mod.funcs[qual], mod, flag)

    # -- missing-donation at the jit site ----------------------------------
    def _check_jit_site(self, jit_call, mod, index, flag):
        qual = enclosing_qualname(mod, jit_call, default="")
        encl = mod.funcs.get(qual)
        targets = _jit_targets(jit_call, mod, qual, index)
        relevant = []
        for fi in targets:
            if fi.qualname in self._surfaces_of(fi.module) or \
                    fi.is_surface:
                relevant.append(fi)
        if not relevant and encl is not None and encl.is_surface:
            # hapi-style builder: jit inside a @jit_surface builder
            relevant = targets
        if not relevant:
            return
        has_donation, _ = _donated_positions(jit_call)
        if has_donation:
            return
        for fi in relevant:
            state = _state_params(fi.node)
            if not state:
                continue
            flag(jit_call, qual or fi.qualname, "missing-donation",
                 f"jit surface `{fi.qualname}` takes state-tree "
                 f"argument(s) {state} but the jax.jit call declares no "
                 "donate_argnums — un-donated state round-trips HBM "
                 "every dispatch (input and output buffers both live). "
                 "Donate the consumed trees, or pragma this line with "
                 "the reason they must outlive the call",
                 fi.qualname)

    # -- caller-side flow: use-after-donate / double / reentry -------------
    def _check_caller(self, fi, mod, flag):
        body = fi.node
        donating = {}   # name -> set(donated positions)
        jitted = set()  # names bound to any jitted callable

        # first sweep: bindings, plus assign-targets of each call so
        # `params = g(params, x)` rebinds (the donated name now holds
        # the RESULT, which is valid)
        call_targets = {}
        for n in ast.walk(body):
            if not isinstance(n, ast.Assign):
                continue
            if isinstance(n.value, ast.Call):
                names = [x for t in n.targets
                         for x in assign_names(t)]
                call_targets[id(n.value)] = names
            jc = _unwrap_jit(n.value, mod)
            if jc is None:
                continue
            has, pos = _donated_positions(jc)
            for t in n.targets:
                if isinstance(t, ast.Name):
                    jitted.add(t.id)
                    if has and pos:
                        donating[t.id] = set(pos)

        if not jitted:
            return

        # second sweep: calls in line order; then uses after them
        events = []   # (lineno, col, kind, payload)
        for n in ast.walk(body):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                if n.func.id in jitted:
                    events.append((n.lineno, n.col_offset, "call", n))
            elif isinstance(n, ast.Name):
                events.append((n.lineno, n.col_offset,
                               "store" if isinstance(n.ctx, ast.Store)
                               else "load", n))
        events.sort(key=lambda e: (e[0], e[1]))

        donated_vars = {}   # name -> (call node, position)
        for lineno, col, kind, n in events:
            if kind == "call":
                fname = n.func.id
                pos = donating.get(fname, set())
                seen = {}
                for i, a in enumerate(n.args):
                    if not isinstance(a, ast.Name):
                        continue
                    if a.id in donated_vars:
                        call0, p0 = donated_vars[a.id]
                        if call0 is not n:
                            flag(n, fi.qualname, "donated-reentry",
                                 f"`{a.id}` was donated to "
                                 f"`{call0.func.id}` (arg {p0}) and is "
                                 f"re-entered into jitted `{fname}` — "
                                 "the donated buffer is invalid (or "
                                 "silently aliased); thread the "
                                 "returned value instead",
                                 f"{a.id}->{fname}")
                            donated_vars.pop(a.id, None)
                    if i in pos:
                        if a.id in seen:
                            flag(n, fi.qualname, "double-donation",
                                 f"`{a.id}` is passed in two donated "
                                 f"positions ({seen[a.id]} and {i}) of "
                                 f"one call — XLA aliases one backing "
                                 "buffer to two outputs; pass "
                                 "independent buffers (cf. "
                                 "_reown_params in nn/layer/"
                                 "transformer.py)",
                                 f"{a.id}:{seen[a.id]}:{i}")
                        else:
                            seen[a.id] = i
                            # `params = g(params, x)` rebinds the name
                            # to the RESULT — don't track it as dead;
                            # double-donation above still sees it
                            if a.id not in call_targets.get(id(n), ()):
                                donated_vars[a.id] = (n, i)
            elif kind == "store" and n.id in donated_vars:
                del donated_vars[n.id]     # rebound: old binding gone
            elif kind == "load" and n.id in donated_vars:
                call0, p0 = donated_vars[n.id]
                # the donating call's own argument list re-walks here —
                # ignore loads on the call line at/after its column
                if n.lineno < call0.lineno or (
                        n.lineno == call0.lineno and
                        n.col_offset <= call0.col_offset):
                    continue
                end = getattr(call0, "end_lineno", call0.lineno)
                if call0.lineno <= n.lineno <= end:
                    continue
                flag(n, fi.qualname, "use-after-donate",
                     f"`{n.id}` was donated to `{call0.func.id}` "
                     f"(arg {p0}) and read afterwards — the buffer is "
                     "invalidated by donation; use the call's returned "
                     "value (or drop the donation)",
                     f"{n.id}")
                del donated_vars[n.id]
