"""Static dtype-propagation pass over the declared-bf16 hot paths.

The mixed-precision contract is directional: the hot paths compute in
bf16 (or the KV cache's narrow wire dtype) and widen to fp32 only at
*declared accumulator* sites — xent/softmax logits, guardian
reductions, EQuARX partial sums.  Three silent ways to break it:

1. ``fp32-upcast`` — a literal ``.astype(jnp.float32)`` inside a
   monitored module or jit surface that is not in the
   ``FP32_CONTRACT_CASTS`` allowlist.  An accidental upcast doubles
   the bytes of everything downstream and XLA will happily keep the
   whole tail of the graph in fp32.
2. ``untyped-alloc`` — a dtype-less ``jnp.zeros``/``ones``/``full``/
   ``empty`` allocation in the same scope: the default dtype is fp32,
   so the allocation silently re-widens whatever flows through it.
   The fix is always to say what you mean (any explicit dtype passes).
3. ``unpaired-quantize`` / ``unscaled-narrow-cast`` — the quantization
   pairing contracts: ``quantize_kv``/``dequantize_kv`` call sites
   must stay balanced per module (``KV_QUANT_PAIRS``); every EQuARX
   ``_to_narrow`` call needs a widening fp32 dequant in the same
   function; and any ``.astype(int8/fp8)`` narrowing must show scale
   handling (a ``*scale*``/``*amax*`` name) in its enclosing function
   or carry a ``NARROW_CAST_CONTRACT`` entry — the machine check the
   fp8 train pilot's delayed-scaling amax state will need.

Scope rule (the host-sync pattern): ``DTYPE_MONITORED_MODULES`` are
checked wholesale; jit-surface functions are checked wherever they
live, fixtures included.  The narrow-cast check is tree-wide — a
scale-free quantize is never right.
"""
import ast

from .base import Finding, call_terminal, dotted, enclosing_qualname
from .allowlist import (DTYPE_MONITORED_MODULES, FP32_CONTRACT_CASTS,
                        NARROW_CAST_CONTRACT, KV_QUANT_PAIRS,
                        EQUARX_NARROW_CALLEES, EXTRA_JIT_SURFACES)

PASS_NAME = "dtype-flow"

# jnp allocators whose dtype defaults to fp32 when omitted
_ALLOC_CALLEES = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}


def _is_fp32_dtype(expr):
    name = dotted(expr)
    if name and name.split(".")[-1] == "float32":
        return True
    return isinstance(expr, ast.Constant) and expr.value == "float32"


def _is_narrow_dtype(expr):
    name = dotted(expr)
    if name:
        last = name.split(".")[-1]
        if last == "int8" or last.startswith("float8"):
            return True
        if last == "_FP8_DTYPE":
            return True
    return isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
        and (expr.value == "int8" or expr.value.startswith("float8"))


def _astype_arg(call):
    """The dtype argument of an ``x.astype(...)`` call, or None."""
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "astype" and len(call.args) == 1:
        return call.args[0]
    return None


def _is_jnp_call(call, mod):
    name = dotted(call.func)
    if not name or "." not in name:
        return False
    root = name.split(".", 1)[0]
    target = mod.alias_module(root) or root
    return target in ("jax.numpy", "jnp") or target.startswith("jax.numpy.")


def _has_dtype_arg(call, n_pos):
    """True when an allocator call pins its dtype (positional index
    ``n_pos`` or a ``dtype=`` keyword)."""
    if len(call.args) > n_pos:
        return True
    return any(kw.arg == "dtype" for kw in call.keywords)


def _scale_evidence(node):
    """True when any identifier under ``node`` carries scale/amax
    handling."""
    for n in ast.walk(node):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        elif isinstance(n, ast.arg):
            ident = n.arg
        if ident is not None:
            low = ident.lower()
            if "scale" in low or "amax" in low:
                return True
    return False


def _contract_entry(table, relpath, qual):
    for (rel, q), reason in table.items():
        if q == qual and (relpath == rel or relpath.endswith("/" + rel)):
            return reason
    return None


class DtypeFlowPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            monitored = any(mod.relpath == m or mod.relpath.endswith("/" + m)
                            for m in DTYPE_MONITORED_MODULES)
            surfaces = {q for q, fi in mod.funcs.items() if fi.is_surface}
            for rel, qual in EXTRA_JIT_SURFACES:
                if (mod.relpath == rel or mod.relpath.endswith("/" + rel)) \
                        and qual in mod.funcs:
                    surfaces.add(qual)
            self._scan(mod, monitored, surfaces, findings)
        return sorted(findings, key=Finding.sort_key)

    def _scan(self, mod, monitored, surfaces, findings):
        def flag(node, code, qual, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(
                self.name, mod.relpath, node.lineno, qual, code, message,
                detail))

        kv_calls = {}        # callee -> first call node (pairing check)
        narrow_by_func = {}  # qual -> [narrow-wrapper call nodes]
        widen_by_func = set()  # quals containing an fp32 widen
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            term = call_terminal(n.func)
            dtype_expr = _astype_arg(n)
            qual = None
            in_scope = False
            if monitored or surfaces:
                if dtype_expr is not None or (
                        term in _ALLOC_CALLEES or term in
                        EQUARX_NARROW_CALLEES or
                        any(term == q or term == d
                            for q, d in KV_QUANT_PAIRS)):
                    qual = enclosing_qualname(mod, n)
                    in_scope = monitored or any(
                        qual == s or qual.startswith(s + ".")
                        for s in surfaces)
            # 1. fp32 upcasts + the widen inventory for the EQuARX check
            if dtype_expr is not None and _is_fp32_dtype(dtype_expr):
                qual = qual or enclosing_qualname(mod, n)
                widen_by_func.add(qual)
                if in_scope and \
                        _contract_entry(FP32_CONTRACT_CASTS, mod.relpath,
                                        qual) is None:
                    flag(n, "fp32-upcast", qual,
                         f"literal fp32 upcast in declared-bf16 hot path "
                         f"`{qual}` — if this is an accumulator that is "
                         "fp32 by contract, add a FP32_CONTRACT_CASTS "
                         "entry in paddle_tpu/analysis/allowlist.py "
                         "with the reason; otherwise keep the compute "
                         "dtype", "float32")
            # 2. dtype-less allocations
            if in_scope and term in _ALLOC_CALLEES and \
                    _is_jnp_call(n, mod) and \
                    not _has_dtype_arg(n, _ALLOC_CALLEES[term]):
                flag(n, "untyped-alloc", qual,
                     f"dtype-less `jnp.{term}` in declared-bf16 hot "
                     f"path `{qual}` allocates fp32 by default — pass "
                     "an explicit dtype (the compute dtype, or fp32 if "
                     "that is the contract, but say so)", term)
            # 3a. kv quantize/dequantize pairing inventory
            if term is not None:
                for q, d in KV_QUANT_PAIRS:
                    if term in (q, d):
                        kv_calls.setdefault(term, n)
                if term in EQUARX_NARROW_CALLEES:
                    qual = qual or enclosing_qualname(mod, n)
                    narrow_by_func.setdefault(qual, []).append(n)
            # 3b. narrow casts need scale handling (tree-wide)
            if dtype_expr is not None and _is_narrow_dtype(dtype_expr):
                qual = qual or enclosing_qualname(mod, n)
                fi = mod.funcs.get(qual)
                scope_node = fi.node if fi is not None else mod.tree
                if not _scale_evidence(scope_node) and \
                        _contract_entry(NARROW_CAST_CONTRACT,
                                        mod.relpath, qual) is None:
                    flag(n, "unscaled-narrow-cast", qual,
                         f"narrow-dtype cast in `{qual}` with no "
                         "scale/amax handling in the same function — "
                         "an unscaled int8/fp8 quantize clips instead "
                         "of scaling; thread the scale group through, "
                         "or add a NARROW_CAST_CONTRACT entry "
                         "(paddle_tpu/analysis/allowlist.py) saying "
                         "where the scale lives", "narrow")
        # module-scope kv pairing verdicts
        for q, d in KV_QUANT_PAIRS:
            if q in kv_calls and d not in kv_calls:
                n = kv_calls[q]
                flag(n, "unpaired-quantize",
                     enclosing_qualname(mod, n),
                     f"`{q}` is called here but `{d}` never is in this "
                     "module — quantized values read back as raw ints "
                     "somewhere; keep the pair together or route reads "
                     "through the dequant helper", f"{q}-without-{d}")
            elif d in kv_calls and q not in kv_calls:
                n = kv_calls[d]
                flag(n, "unpaired-quantize",
                     enclosing_qualname(mod, n),
                     f"`{d}` is called here but `{q}` never is in this "
                     "module — dequantizing data nothing quantized "
                     "produces garbage scaled by a stale sidecar; keep "
                     "the pair together", f"{d}-without-{q}")
        # EQuARX: every narrowing function must widen back to fp32
        for qual, nodes in sorted(narrow_by_func.items()):
            if qual not in widen_by_func:
                flag(nodes[0], "unpaired-quantize", qual,
                     f"`{qual}` narrows with "
                     f"{'/'.join(sorted(EQUARX_NARROW_CALLEES))} but "
                     "never widens back with an fp32 dequant in the "
                     "same function — the EQuARX wire value is useless "
                     "until rescaled; dequantize (`.astype(jnp."
                     "float32) * scale`) before reducing",
                     "narrow-without-dequant")
