"""Host-sync budget pass: machine-check the one-sync-per-step contract.

PR 2 fused ``GradScaler.unscale_`` to exactly ONE host sync per step and
funneled every sentinel readback through ``guardian._host_bool`` so
tests can count syncs at runtime.  That contract lived in comments; this
pass makes it structural: every explicit sync site — ``_host_bool``,
``.item()``/``.numpy()``, ``np.asarray``, ``device_get``,
``block_until_ready`` — inside the monitored hot-path modules must match
a budgeted entry in ``allowlist.HOST_SYNC_ALLOWLIST`` (with a reason),
and a function may not grow more sites than its budget.

Jit-surface functions are additionally monitored wherever they live
(including fixture files): a sync primitive inside a surface is always a
finding — there is no legal budget for a sync inside a trace.
"""
import ast

from .base import Finding, call_terminal, dotted, enclosing_qualname
from .allowlist import (MONITORED_MODULES, SYNC_CALLEES, NUMPY_SYNC_FUNCS,
                        HOST_SYNC_ALLOWLIST, EXTRA_JIT_SURFACES)

PASS_NAME = "host-sync"


def _sync_callee(call, mod):
    """Canonical callee token if this call is a sync primitive."""
    term = call_terminal(call.func)
    if term in SYNC_CALLEES:
        # `.item()`/`.numpy()` style readbacks are only syncs as
        # zero-arg attribute calls; `_host_bool`/`device_get`/... match
        # as plain names or module attributes
        if term in ("item", "numpy", "tolist") and (
                not isinstance(call.func, ast.Attribute) or call.args):
            return None
        return term
    if term in NUMPY_SYNC_FUNCS:
        name = dotted(call.func)
        if name:
            root = name.split(".", 1)[0]
            target = mod.alias_module(root) or root
            if target == "numpy" or target.startswith("numpy."):
                return term
    return None


class HostSyncPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            monitored = any(mod.relpath == m or mod.relpath.endswith("/" + m)
                            for m in MONITORED_MODULES)
            surfaces = {q for q, fi in mod.funcs.items() if fi.is_surface}
            # nested surfaces the decorator can't reach are surfaces too
            for rel, qual in EXTRA_JIT_SURFACES:
                if (mod.relpath == rel or mod.relpath.endswith("/" + rel)) \
                        and qual in mod.funcs:
                    surfaces.add(qual)
            if not monitored and not surfaces:
                continue
            self._scan(mod, monitored, surfaces, findings)
        return sorted(findings, key=Finding.sort_key)

    def _scan(self, mod, monitored, surfaces, findings):
        # budget key -> [(node, qualname, callee), ...]
        sites = {}
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            callee = _sync_callee(n, mod)
            if callee is None:
                continue
            qual = enclosing_qualname(mod, n)
            in_surface = any(qual == s or qual.startswith(s + ".")
                             for s in surfaces)
            if in_surface:
                if {self.name, "sync-in-jit-surface"} & \
                        mod.allowed_on_line(n.lineno):
                    continue
                findings.append(Finding(
                    self.name, mod.relpath, n.lineno, qual,
                    "sync-in-jit-surface",
                    f"sync primitive `{callee}` inside jit surface "
                    f"`{qual}` — a traced step may never read back to "
                    "host; keep the verdict on device and sync once "
                    "outside the trace", callee))
                continue
            if monitored:
                sites.setdefault((qual, callee), []).append(n)
        # check budgets for the monitored-module inventory
        for (qual, callee), nodes in sorted(sites.items()):
            # pragma'd sites are exempt BEFORE budgeting — a justified
            # `# lint: allow(...)` site must not consume a budget slot
            # and shift the finding onto an untouched allowlisted line
            nodes = [x for x in nodes
                     if not ({self.name, "unbudgeted-host-sync"}
                             & mod.allowed_on_line(x.lineno))]
            nodes.sort(key=lambda n: (n.lineno, n.col_offset))
            entry = self._allow_entry(mod.relpath, qual, callee)
            budget = entry["max"] if entry else 0
            for extra in nodes[budget:]:
                if entry:
                    msg = (f"`{qual}` has {len(nodes)} `{callee}` sync "
                           f"site(s) but its allowlist budget is "
                           f"{budget} — the one-sync-per-step contract "
                           "only holds if new readbacks replace old "
                           "ones, not stack on top")
                else:
                    msg = (f"unbudgeted host sync `{callee}` in hot-path "
                           f"function `{qual}` — if this readback is "
                           "intentional, add a HOST_SYNC_ALLOWLIST entry "
                           "in paddle_tpu/analysis/allowlist.py with a "
                           "reason (see docs/static_analysis.md)")
                findings.append(Finding(
                    self.name, mod.relpath, extra.lineno, qual,
                    "unbudgeted-host-sync", msg, callee))

    @staticmethod
    def _allow_entry(relpath, qual, callee):
        for (rel, q, c), entry in HOST_SYNC_ALLOWLIST.items():
            if c == callee and q == qual and (
                    relpath == rel or relpath.endswith("/" + rel)):
                return entry
        return None
