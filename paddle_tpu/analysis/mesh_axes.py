"""Mesh/PartitionSpec consistency pass: the sharding-annotation bug
class that otherwise surfaces only at trace time (or worse, as a
silently wrong-layout reshard).

Every axis name the framework hardcodes must come from the
machine-checked ``MESH_AXES`` vocabulary (``allowlist.py``) — a typo'd
``P("dta")`` resolves to *replicated* under GSPMD's unknown-axis
handling or throws deep inside a shard_map trace, neither of which
names the offending literal.  Four shapes are flagged:

1. ``undeclared-axis`` — a ``PartitionSpec``/``P(...)`` literal,
   ``shard_map`` spec, or collective ``axis_name=`` naming an axis not
   in ``MESH_AXES``.
2. ``duplicate-axis`` — the same axis used twice in one spec
   (``P("data", "data")`` is invalid: an array dim can shard over an
   axis only once).
3. ``spec-arity-mismatch`` — a ``shard_map`` whose literal ``in_specs``
   tuple length cannot match the wrapped function's positional arity
   (the error XLA reports as an opaque pytree mismatch).
4. ``unbound-axis-name`` — a ``psum``/``all_gather``/``ppermute``/
   ``all_to_all``/``axis_index`` call whose *literal* axis name is not
   bound by any ``shard_map``/``Mesh``/``axis_name=`` declaration in
   the same module (the collective_order.py walk extended to axis
   binding; the runtime error is an unbound-axis NameError mid-trace).

Variable axis arguments (``lax.psum(x, axis)``) resolve dynamically and
are deliberately not flagged — the vocabulary check applies where the
literal appears (the defaults and specs that feed those variables).
"""
import ast

from .base import Finding, call_terminal, dotted, enclosing_qualname
from .allowlist import MESH_AXES

PASS_NAME = "mesh-axes"

# collective callee -> positional index of its axis-name argument
COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "ppermute": 1, "all_to_all": 1, "axis_index": 0,
}

_SHARD_MAP_CALLEES = ("shard_map", "_shard_map")


def _is_pspec_call(call, mod):
    """True for ``PartitionSpec(...)`` / aliased ``P(...)`` calls."""
    if call_terminal(call.func) == "PartitionSpec":
        return True
    if isinstance(call.func, ast.Name):
        target = mod.alias_module(call.func.id) or ""
        return target.split(".")[-1] == "PartitionSpec"
    return False


def _axis_literals(node):
    """(name, node) for every string constant under ``node`` — the
    axis names a spec/axis argument can carry (bare, tupled, or inside
    an IfExp arm)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append((n.value, n))
    return out


def _spec_value_literals(call):
    """(name, node) for string constants in *value positions* of a
    spec call: direct arguments, tuple/list elements, and IfExp arms.
    Unlike :func:`_axis_literals` this does not descend into IfExp
    tests or comparisons, so ``P("data" if "data" in dims else None)``
    counts ``"data"`` once, not twice."""
    out = []

    def walk_value(e):
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append((e.value, e))
        elif isinstance(e, (ast.Tuple, ast.List)):
            for elt in e.elts:
                walk_value(elt)
        elif isinstance(e, ast.IfExp):
            walk_value(e.body)
            walk_value(e.orelse)

    for a in call.args:
        walk_value(a)
    for kw in call.keywords:
        walk_value(kw.value)
    return out


def _positional_arity(fnode):
    """(min, max) positional-argument count of a function node, or
    None when ``*args`` makes it unbounded."""
    a = fnode.args
    if a.vararg is not None:
        return None
    pos = [p.arg for p in a.posonlyargs + a.args]
    if pos and pos[0] in ("self", "cls"):
        pos = pos[1:]
    n = len(pos)
    return (n - len(a.defaults), n)


def _collective_axis_arg(call):
    """The axis-name argument expression of a collective call, or
    None."""
    term = call_terminal(call.func)
    if term not in COLLECTIVE_AXIS_ARG:
        return None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    idx = COLLECTIVE_AXIS_ARG[term]
    if len(call.args) > idx:
        return call.args[idx]
    return None


def _shard_map_parts(call):
    """(fn_expr, in_specs_expr, out_specs_expr) of a shard_map call,
    any of them None when absent."""
    fn = call.args[0] if call.args else None
    parts = {"in_specs": None, "out_specs": None}
    for kw in call.keywords:
        if kw.arg in parts:
            parts[kw.arg] = kw.value
    # the positional compat shape: _shard_map(f, mesh, in, out)
    if parts["in_specs"] is None and len(call.args) > 2:
        parts["in_specs"] = call.args[2]
    if parts["out_specs"] is None and len(call.args) > 3:
        parts["out_specs"] = call.args[3]
    return fn, parts["in_specs"], parts["out_specs"]


class MeshAxesPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            self._scan(ctx, mod, findings)
        return sorted(findings, key=Finding.sort_key)

    # -- per-module ---------------------------------------------------------
    def _scan(self, ctx, mod, findings):
        def flag(node, code, qual, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(
                self.name, mod.relpath, node.lineno, qual, code, message,
                detail))

        bound = self._bound_axes(mod)
        shard_map_calls = []
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            qual = None  # lazily computed
            if _is_pspec_call(n, mod):
                qual = enclosing_qualname(mod, n)
                self._check_spec(n, qual, flag)
            axis_expr = _collective_axis_arg(n)
            if axis_expr is not None:
                qual = qual or enclosing_qualname(mod, n)
                term = call_terminal(n.func)
                for name, node in _axis_literals(axis_expr):
                    if name not in MESH_AXES:
                        flag(node, "undeclared-axis", qual,
                             f"collective `{term}` names axis {name!r} "
                             "which is not in the MESH_AXES vocabulary "
                             "(paddle_tpu/analysis/allowlist.py) — a "
                             "typo'd axis fails at trace time without "
                             "naming the literal; fix the name or "
                             "extend the vocabulary deliberately",
                             f"{term}:{name}")
                    elif name not in bound:
                        flag(node, "unbound-axis-name", qual,
                             f"collective `{term}` names axis {name!r} "
                             "but no shard_map/Mesh/axis_name "
                             "declaration in this module binds it — "
                             "the trace dies with an unbound-axis "
                             "error on the first dispatch; bind the "
                             "axis (shard_map specs / mesh axis_names) "
                             "or thread it in as a parameter",
                             f"{term}:{name}")
            if call_terminal(n.func) in _SHARD_MAP_CALLEES:
                shard_map_calls.append(n)
        for call in shard_map_calls:
            self._check_shard_map(ctx, mod, call, flag)

    # -- specs ---------------------------------------------------------------
    def _check_spec(self, call, qual, flag):
        seen = {}
        for name, node in _spec_value_literals(call):
            if name not in MESH_AXES:
                flag(node, "undeclared-axis", qual,
                     f"PartitionSpec names axis {name!r} which is not "
                     "in the MESH_AXES vocabulary "
                     "(paddle_tpu/analysis/allowlist.py) — under GSPMD "
                     "an unknown axis is an opaque trace-time error, "
                     "or worse a silently replicated dim; fix the name "
                     "or extend the vocabulary deliberately",
                     f"P:{name}")
            first = seen.get(name)
            if first is not None:
                flag(node, "duplicate-axis", qual,
                     f"axis {name!r} appears twice in one "
                     "PartitionSpec — an array can shard over a mesh "
                     "axis only once; the second use is either a typo "
                     "for another axis or an invalid spec",
                     f"P:{name}")
            else:
                seen[name] = node

    # -- shard_map arity -----------------------------------------------------
    def _check_shard_map(self, ctx, mod, call, flag):
        fn_expr, in_specs, _ = _shard_map_parts(call)
        if not isinstance(in_specs, (ast.Tuple, ast.List)):
            return           # single broadcast spec or computed tuple
        if any(isinstance(e, ast.Starred) for e in in_specs.elts):
            return
        qual = enclosing_qualname(mod, call)
        fi = None
        if isinstance(fn_expr, ast.Name):
            fi = ctx.index.resolve_call(mod, qual, fn_expr)
        if fi is None:
            return
        arity = _positional_arity(fi.node)
        if arity is None:
            return
        lo, hi = arity
        n = len(in_specs.elts)
        if not (lo <= n <= hi):
            want = str(hi) if lo == hi else f"{lo}..{hi}"
            flag(call, "spec-arity-mismatch", qual,
                 f"shard_map in_specs has {n} spec(s) but the wrapped "
                 f"function `{fi.qualname}` takes {want} positional "
                 "argument(s) — the mismatch surfaces as an opaque "
                 "pytree-structure error at trace time; keep specs and "
                 "signature in lockstep",
                 f"{fi.qualname}:{n}")

    # -- module-level axis bindings ------------------------------------------
    @staticmethod
    def _bound_axes(mod):
        """Axis names bound somewhere in the module: shard_map spec
        literals, ``Mesh(..., (names))`` constructions, and
        ``axis_name=`` keyword literals (pmap/vmap/shard_map)."""
        bound = set()
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            term = call_terminal(n.func)
            if term in _SHARD_MAP_CALLEES:
                for name, _ in _axis_literals(n):
                    bound.add(name)
            elif term == "Mesh" and len(n.args) > 1:
                for name, _ in _axis_literals(n.args[1]):
                    bound.add(name)
            for kw in n.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    for name, _ in _axis_literals(kw.value):
                        bound.add(name)
        return bound
