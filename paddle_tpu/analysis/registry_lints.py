"""Registry lints: the failpoint-reference and guardian-log-schema
checks that used to live in ``tools/check_failpoints.py`` and
``tools/check_guardian_log.py``, folded into the unified framework
(the tools remain as thin wrappers over these passes).

Unlike the AST passes these import the live framework — the failpoint
registry and ``EVENT_SCHEMA`` are populated at import time, which is
exactly the point: the lint compares *references* (tests/docs) against
the *registration reality* of the running code.
"""
import os
import re

from .base import Finding, read_text

# name references: a set_failpoint call with a quoted name, and
# PADDLE_FAILPOINTS-shaped spec strings (name=action[;...]).  The
# comments here deliberately avoid writing a matchable literal — this
# very file is scanned when the lint runs over explicit paths.
_SET_RE = re.compile(r"set_failpoint\(\s*[\"']([^\"']+)[\"']")
_SPEC_RE = re.compile(r"[\"']([a-z0-9_]+(?:\.[a-z0-9_]+)+=[^\"']+)[\"']")

# guardian-log references: an emit/events call with a quoted event
# (positional or event=), and the docs schema table rows
_CALL_RE = re.compile(
    r"\b(?:emit|events)\(\s*(?:event\s*=\s*)?[\"']([a-z_]+)[\"']")
_ROW_RE = re.compile(r"^\|\s*`([a-z_]+)`\s*\|\s*`([^`]*)`", re.M)

GUARDIAN_DOC = "docs/training_guardian.md"

# metrics-registry references: any pt_<subsystem>_... token (quoted,
# backticked or bare) in tests/docs.  Scoping mirrors the failpoint
# lint: only tokens whose subsystem prefix the catalog registers count,
# so an unrelated pt_batch_* shm tag never fails this lint.
_METRIC_RE = re.compile(r"\b(pt_[a-z0-9]+_[a-z0-9_]+)\b")
# the observability doc's catalog table rows: | `name` | `type` | `labels` |
_METRIC_ROW_RE = re.compile(
    r"^\|\s*`(pt_[a-z0-9_]+)`\s*\|\s*`([a-z]+)`\s*\|\s*`([^`]*)`", re.M)

# the watch-rule table (ISSUE 13): rows | `rule` | `signal` |
# `trips_when` | meaning |, scoped to the doc's "Watch rules" section
# so the metric table's rows (same pipe shape) never collide
_WATCH_SECTION_RE = re.compile(r"^##[^\n]*watch rules[^\n]*$",
                               re.I | re.M)
_WATCH_ROW_RE = re.compile(
    r"^\|\s*`([a-z_]+)`\s*\|\s*`([^`]*)`\s*\|\s*`([^`]*)`", re.M)

OBSERVABILITY_DOC = "docs/observability.md"


def _read(path):
    # shared mtime-keyed cache: several passes read the same tests/docs
    # corpus per sweep
    return read_text(path)


def _line_of(text, match):
    return text.count("\n", 0, match.start()) + 1


class FailpointRefsPass:
    """Every failpoint name referenced by tests/docs must exist in the
    registry — a renamed hook site must not leave chaos tests arming a
    failpoint that can never fire."""

    name = "failpoint-refs"

    def _registry(self):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ..framework import failpoints
        # importing the hooked modules populates the registry
        import paddle_tpu.framework.guardian        # noqa: F401
        import paddle_tpu.distributed.store         # noqa: F401
        import paddle_tpu.distributed.checkpoint    # noqa: F401
        import paddle_tpu.distributed.collective    # noqa: F401
        import paddle_tpu.distributed.fleet.elastic  # noqa: F401
        import paddle_tpu.io.worker                 # noqa: F401
        import paddle_tpu.inference.router          # noqa: F401
        import paddle_tpu.inference.handoff         # noqa: F401
        return failpoints

    def run(self, ctx):
        failpoints = self._registry()
        known = failpoints.registered()
        prefixes = {n.split(".", 1)[0] for n in known}
        findings = []
        for path in ctx.ref_files:
            rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
            text = _read(path)
            for m in _SET_RE.finditer(text):
                if m.group(1) not in known:
                    findings.append(self._finding(rel, text, m, m.group(1)))
            for m in _SPEC_RE.finditer(text):
                try:
                    parsed = failpoints.parse_spec(m.group(1))
                except ValueError:
                    continue    # merely spec-shaped; not a spec
                for n in sorted(parsed):
                    # only names carrying a registered subsystem prefix
                    # count — an unrelated "retry.mode=skip" literal in a
                    # test must not fail this lint
                    if n.split(".", 1)[0] in prefixes and n not in known:
                        findings.append(self._finding(rel, text, m, n))
        return sorted(findings, key=Finding.sort_key)

    def _finding(self, rel, text, match, name):
        return Finding(
            self.name, rel, _line_of(text, match), "<text>",
            "orphan-failpoint",
            f"failpoint {name!r} is referenced but not registered — the "
            "chaos test silently stops testing anything; register the "
            "site in the hooked module or fix the name", name)


class GuardianLogSchemaPass:
    """Guardian-log events referenced by tests/docs must match the
    emitter's EVENT_SCHEMA, and the docs schema table must mirror it
    field-for-field (dashboards are built from the doc)."""

    name = "guardian-log"

    def run(self, ctx):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ..framework.guardian import EVENT_SCHEMA
        findings = []
        for path in ctx.ref_files:
            rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
            text = _read(path)
            for m in _CALL_RE.finditer(text):
                if m.group(1) not in EVENT_SCHEMA:
                    findings.append(Finding(
                        self.name, rel, _line_of(text, m), "<text>",
                        "unknown-guardian-event",
                        f"unknown guardian event {m.group(1)!r} (known: "
                        f"{sorted(EVENT_SCHEMA)})", m.group(1)))
        doc = os.path.join(ctx.root, GUARDIAN_DOC)
        # the table check runs whenever the guardian doc is in scope —
        # an explicit `docs/` run must check the table, not skip it
        in_scope = ctx.default_tree or any(
            os.path.abspath(p) == os.path.abspath(doc)
            for p in ctx.ref_files)
        if in_scope:
            findings.extend(self._check_doc_table(doc, EVENT_SCHEMA))
        return sorted(findings, key=Finding.sort_key)

    def _check_doc_table(self, doc, schema):
        findings = []
        if not os.path.exists(doc):
            return [Finding(self.name, GUARDIAN_DOC, 1, "<doc>",
                            "schema-drift",
                            "docs/training_guardian.md is missing (the "
                            "guardian log schema must be documented)",
                            "missing-doc")]
        text = _read(doc)
        table = {}
        for m in _ROW_RE.finditer(text):
            table[m.group(1)] = (
                {f.strip() for f in m.group(2).split(",") if f.strip()},
                _line_of(text, m))
        for name, (fields, line) in sorted(table.items()):
            if name not in schema:
                findings.append(Finding(
                    self.name, GUARDIAN_DOC, line, "<doc>", "schema-drift",
                    f"documents unknown event {name!r}", name))
            elif fields != schema[name]:
                findings.append(Finding(
                    self.name, GUARDIAN_DOC, line, "<doc>", "schema-drift",
                    f"event {name!r} fields {sorted(fields)} drifted from "
                    f"emitter schema {sorted(schema[name])}", name))
        for name in sorted(schema):
            if name not in table:
                findings.append(Finding(
                    self.name, GUARDIAN_DOC, 1, "<doc>", "schema-drift",
                    f"event {name!r} is emitted but undocumented", name))
        return findings


class MetricNamesPass:
    """Metric names referenced by tests/docs must exist in the
    observability catalog, and the docs catalog table must mirror it
    row-for-row (type + labels) — the guardian-log contract applied to
    the metrics registry: dashboards and alerts are built from names,
    so a renamed metric must fail lint, not silently flatline a graph.
    """

    name = "metrics-registry"

    def _catalog(self):
        import os as _os
        _os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ..observability.catalog import METRICS, subsystems
        return METRICS, subsystems()

    def run(self, ctx):
        metrics, subs = self._catalog()
        findings = []
        for path in ctx.ref_files:
            rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
            text = _read(path)
            for m in _METRIC_RE.finditer(text):
                token = m.group(1)
                # strip prometheus exposition suffixes so a _bucket/
                # _sum/_count sample in a doc example resolves to its
                # base histogram
                base = token
                for suf in ("_bucket", "_sum", "_count"):
                    if base.endswith(suf) and base[:-len(suf)] in metrics:
                        base = base[:-len(suf)]
                if base.split("_", 2)[1] in subs and base not in metrics:
                    findings.append(Finding(
                        self.name, rel, _line_of(text, m), "<text>",
                        "unknown-metric",
                        f"metric {token!r} is referenced but not in the "
                        "observability catalog — a dashboard built on it "
                        "would silently flatline; declare it in "
                        "paddle_tpu/observability/catalog.py or fix the "
                        "name", token))
        doc = os.path.join(ctx.root, OBSERVABILITY_DOC)
        in_scope = ctx.default_tree or any(
            os.path.abspath(p) == os.path.abspath(doc)
            for p in ctx.ref_files)
        if in_scope:
            findings.extend(self._check_doc_table(doc, metrics))
            findings.extend(self._check_watch_table(doc))
        return sorted(findings, key=Finding.sort_key)

    def _check_doc_table(self, doc, metrics):
        findings = []
        if not os.path.exists(doc):
            return [Finding(self.name, OBSERVABILITY_DOC, 1, "<doc>",
                            "catalog-drift",
                            "docs/observability.md is missing (the metric "
                            "catalog must be documented)", "missing-doc")]
        text = _read(doc)
        table = {}
        for m in _METRIC_ROW_RE.finditer(text):
            labels = {f.strip() for f in m.group(3).split(",")
                      if f.strip() and f.strip() != "-"}
            table[m.group(1)] = ((m.group(2), labels), _line_of(text, m))
        for name, ((mtype, labels), line) in sorted(table.items()):
            if name not in metrics:
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, line, "<doc>",
                    "catalog-drift",
                    f"documents unknown metric {name!r}", name))
                continue
            spec = metrics[name]
            want = (spec["type"], set(spec.get("labels", ())))
            if (mtype, labels) != want:
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, line, "<doc>",
                    "catalog-drift",
                    f"metric {name!r} documented as {mtype}/"
                    f"{sorted(labels)} but the catalog declares "
                    f"{want[0]}/{sorted(want[1])}", name))
        for name in sorted(metrics):
            if name not in table:
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, 1, "<doc>",
                    "catalog-drift",
                    f"metric {name!r} is in the catalog but "
                    "undocumented", name))
        return findings

    def _check_watch_table(self, doc):
        """The WatchRule catalog (observability/watch.py) must be
        mirrored row-for-row — name, signal, trips_when — by the doc's
        'Watch rules' section table (the metric/event-table discipline
        applied to alert rules: dashboards route on rule names)."""
        if not os.path.exists(doc):
            return []            # the catalog check already reported it
        from ..observability.watch import WATCH_RULES
        text = _read(doc)
        findings = []
        m = _WATCH_SECTION_RE.search(text)
        if m is None:
            return [Finding(
                self.name, OBSERVABILITY_DOC, 1, "<doc>",
                "watch-rule-drift",
                "docs/observability.md has no 'Watch rules' section — "
                "the WatchRule catalog must be documented "
                "(observability/watch.py WATCH_RULES)", "missing-table")]
        start = m.end()
        nxt = text.find("\n## ", start)
        section = text[start:nxt if nxt != -1 else len(text)]
        offset = text.count("\n", 0, start)
        table = {}
        for row in _WATCH_ROW_RE.finditer(section):
            line = offset + section.count("\n", 0, row.start()) + 1
            table[row.group(1)] = ((row.group(2), row.group(3)), line)
        for name, ((signal, trips), line) in sorted(table.items()):
            spec = WATCH_RULES.get(name)
            if spec is None:
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, line, "<doc>",
                    "watch-rule-drift",
                    f"documents unknown watch rule {name!r}", name))
            elif (signal, trips) != (spec["signal"],
                                     spec["trips_when"]):
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, line, "<doc>",
                    "watch-rule-drift",
                    f"watch rule {name!r} signal/trips_when drifted "
                    "from the WATCH_RULES catalog "
                    "(observability/watch.py)", name))
        for name in sorted(WATCH_RULES):
            if name not in table:
                findings.append(Finding(
                    self.name, OBSERVABILITY_DOC, 1, "<doc>",
                    "watch-rule-drift",
                    f"watch rule {name!r} is in the catalog but "
                    "undocumented", name))
        return findings
