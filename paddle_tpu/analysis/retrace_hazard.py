"""Retrace-hazard pass: the static complement of the runtime
``compile_retrace`` sentinel (observability/compilestats.py).

The sentinel catches a silent recompile *after it happened*; this pass
flags the key/static-arg constructions that cause them *before they
ship*.  The hazard classes (the compilestats docstring's "jit
cache-miss class of perf bug", made lintable):

- ``unbucketed-shape-key`` — a jit cache key (or static argument) built
  from a *data-dependent* dynamic extent: ``len(prompt)`` /
  ``ids.shape`` interpolated into the key compiles one executable per
  request shape.  Route the extent through a bucketing helper first
  (anything named ``*bucket*`` exempts the component — the serving
  engine's ``_bucket_for`` discipline), or pragma the line where the
  per-shape compile is the documented contract (``generate()``).
- ``float-cache-key`` — a *computed* float as a key component: any
  jitter in the value (a ratio, a schedule read) is an unbounded
  retrace stream.  ``float(<plain parameter>)`` canonicalizations are
  exempt — bounded user knobs, exact dict equality.
- ``unordered-key-part`` — dict/set iteration order feeding a cache key
  or static argument (``tuple(set(...))``, ``d.keys()`` unsorted): the
  key varies run-to-run, so warm caches go cold.  Wrap in
  ``sorted(...)``.
- ``uncached-jit-call`` — ``jax.jit(f)(...)`` called inline: the jit
  object is rebuilt (and the program retraced) on every call; hoist the
  jit into a cache or a build-once closure.

Findings are attributed to the SAME surface-name labels the
``pt_compile_*`` telemetry uses: the pass reads the surface string from
the ``compilestats.wrap(...)`` / ``_tracked(...)`` call wrapping the
stored jit (falling back to ``allowlist.SURFACE_LABELS``), so a static
finding and the runtime retrace event for one surface share one
vocabulary (``docs/observability.md``).  Sites that resolve no label
report ``<unlabeled>`` — wrap them.
"""
import ast

from .base import (Finding, call_terminal, dotted, is_jax_jit_call,
                   assign_names, enclosing_qualname, int_literals,
                   param_names, WRAP_CALLEES)
from .allowlist import (COMPILE_SURFACES, SURFACE_LABELS,
                        RETRACE_DATA_TOKENS)

PASS_NAME = "retrace-hazard"

_SHAPEY_CALL_FRAGMENTS = ("shape", "len", "sig")


def _find_jit(expr, mod):
    """The jax.jit Call nested anywhere in ``expr`` (through wrappers,
    tuples, builder-call args), or None."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and is_jax_jit_call(n, mod):
            return n
    return None


def _wrap_labels(expr):
    """Surface-name string literals passed to compilestats wrappers
    inside ``expr``."""
    out = []
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and \
                call_terminal(n.func) in WRAP_CALLEES:
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                for c in ast.walk(a):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str) and \
                            c.value in COMPILE_SURFACES:
                        out.append(c.value)
    return sorted(set(out))


def _is_surface_builder_store(value, mod, index, qual):
    """True when the stored value builds a compiled surface without a
    visible jax.jit — ``self._tracked(self._build_train(...), ...)``:
    a wrapper call whose argument invokes a @jit_surface builder."""
    for n in ast.walk(value):
        if isinstance(n, ast.Call) and \
                call_terminal(n.func) in WRAP_CALLEES:
            for a in n.args:
                if isinstance(a, ast.Call):
                    fi = index.resolve_call(mod, qual, a.func)
                    if fi is not None and fi.is_surface:
                        return True
    return False


def _is_dataish(name):
    toks = set(name.lower().split("_"))
    return bool(toks & RETRACE_DATA_TOKENS)


class _FnFacts:
    """Per-function name facts: which locals are data-derived, which
    carry data-derived *shape* extents, and the latest visible
    assignment expression per name."""

    def __init__(self, fnode):
        self.data = {p for p in param_names(fnode) if _is_dataish(p)}
        self.shapeish = set()
        self.assigns = {}   # name -> value expr (last one wins)
        for _ in range(3):
            before = (len(self.data), len(self.shapeish))
            for n in ast.walk(fnode):
                if not isinstance(n, ast.Assign):
                    continue
                names = [x for t in n.targets for x in assign_names(t)]
                for name in names:
                    self.assigns[name] = n.value
                mentions_data = any(
                    isinstance(c, ast.Name) and c.id in self.data
                    for c in ast.walk(n.value))
                if mentions_data:
                    self.data.update(names)
                    if self._shape_extract(n.value) and \
                            not _through_bucket(n.value):
                        self.shapeish.update(names)
                # a shape extent only stays an extent through SCALAR
                # arithmetic (MAX = P + n); flowing into an array/
                # container/str kills the taint (mask = zeros((B, MAX)))
                if self._scalar_expr(n.value) and any(
                        isinstance(c, ast.Name) and c.id in self.shapeish
                        for c in ast.walk(n.value)):
                    self.shapeish.update(names)
            if (len(self.data), len(self.shapeish)) == before:
                break

    _SCALAR_FUNCS = frozenset({"int", "min", "max", "abs", "round",
                               "len"})

    def _scalar_expr(self, expr):
        """True when ``expr`` is pure scalar arithmetic over names and
        constants (the shape-extent-preserving shapes)."""
        for c in ast.walk(expr):
            if isinstance(c, (ast.Name, ast.Constant, ast.BinOp,
                              ast.UnaryOp, ast.IfExp, ast.Compare,
                              ast.BoolOp, ast.Load, ast.Tuple)):
                continue
            if isinstance(c, ast.Call) and \
                    isinstance(c.func, ast.Name) and \
                    c.func.id in self._SCALAR_FUNCS:
                continue
            if isinstance(c, (ast.Attribute, ast.Subscript)):
                continue          # x.shape[0]-style extent reads
            if isinstance(c, (ast.operator, ast.unaryop, ast.cmpop,
                              ast.boolop, ast.expr_context)):
                continue
            return False
        return True

    def _shape_extract(self, expr):
        """Does ``expr`` read a dynamic extent off a data value —
        ``x.shape`` / ``len(x)`` with x data-derived?"""
        for c in ast.walk(expr):
            if isinstance(c, ast.Attribute) and c.attr == "shape" and \
                    isinstance(c.value, ast.Name) and \
                    c.value.id in self.data:
                return True
            if isinstance(c, ast.Call) and \
                    isinstance(c.func, ast.Name) and c.func.id == "len" \
                    and any(isinstance(a, ast.Name) and a.id in self.data
                            for a in c.args):
                return True
        return False


def _through_bucket(expr):
    """A component routed through anything named ``*bucket*`` is
    bounded by construction."""
    for c in ast.walk(expr):
        if isinstance(c, ast.Call):
            name = dotted(c.func) or ""
            if "bucket" in name.lower():
                return True
        if isinstance(c, ast.Name) and "bucket" in c.id.lower():
            return True
    return False


def _components(key_expr):
    if isinstance(key_expr, (ast.Tuple, ast.List)):
        return list(key_expr.elts)
    return [key_expr]


def _surface_label(mod, qual, store_value):
    labels = _wrap_labels(store_value) if store_value is not None else []
    if not labels and qual:
        fi = mod.funcs.get(qual)
        if fi is not None:
            labels = _wrap_labels(fi.node)
    if labels:
        return "|".join(labels)
    for (rel, q), label in SURFACE_LABELS.items():
        if q == qual and (mod.relpath == rel or
                          mod.relpath.endswith("/" + rel)):
            return label
    return "<unlabeled>"


class RetraceHazardPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for mod in ctx.index.iter_modules():
            self._scan(mod, ctx.index, findings)
        return sorted(findings, key=Finding.sort_key)

    def _scan(self, mod, index, findings):
        def flag(node, qual, code, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(self.name, mod.relpath, node.lineno,
                                    qual, code, message, detail))

        facts_cache = {}

        def facts_for(qual):
            fi = mod.funcs.get(qual)
            if fi is None:
                return None
            if qual not in facts_cache:
                facts_cache[qual] = _FnFacts(fi.node)
            return facts_cache[qual]

        static_jits = {}   # (qual, name) -> static positions

        for n in ast.walk(mod.tree):
            # uncached-jit-call: jax.jit(f)(...) inline
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Call) \
                    and is_jax_jit_call(n.func, mod):
                qual = enclosing_qualname(mod, n, default="")
                flag(n, qual, "uncached-jit-call",
                     "`jax.jit(f)(...)` rebuilds the jit object (and "
                     "retraces) on every call — bind it once and cache "
                     "per signature (compilestats.wrap gives the cached "
                     "surface telemetry for free)", "inline-jit")
                continue
            if not isinstance(n, ast.Assign):
                continue
            jit_call = _find_jit(n.value, mod)
            qual = enclosing_qualname(mod, n, default="")
            # record static_argnums bindings for the call-site check
            if jit_call is not None:
                for kw in jit_call.keywords:
                    if kw.arg == "static_argnums":
                        pos = int_literals(kw.value)
                        for t in n.targets:
                            if isinstance(t, ast.Name) and pos:
                                static_jits[(qual, t.id)] = pos
            # jit-cache-key sites: a Subscript store whose value holds a
            # jit (or builds a tracked surface)
            subs = [t for t in n.targets if isinstance(t, ast.Subscript)]
            if not subs:
                continue
            if jit_call is None and not _is_surface_builder_store(
                    n.value, mod, index, qual):
                continue
            facts = facts_for(qual)
            if facts is None:
                continue
            label = _surface_label(mod, qual, n.value)
            for sub in subs:
                key_expr = sub.slice
                anchor = key_expr
                if isinstance(key_expr, ast.Name):
                    resolved = facts.assigns.get(key_expr.id)
                    if resolved is not None:
                        anchor = resolved
                        key_expr = resolved
                self._check_key(key_expr, anchor, qual, label, facts,
                                mod, flag)

        # static-argnum call sites
        if static_jits:
            for n in ast.walk(mod.tree):
                if not (isinstance(n, ast.Call) and
                        isinstance(n.func, ast.Name)):
                    continue
                qual = enclosing_qualname(mod, n, default="")
                pos = static_jits.get((qual, n.func.id))
                if not pos:
                    continue
                facts = facts_for(qual)
                if facts is None:
                    continue
                label = _surface_label(mod, qual, None)
                for i in pos:
                    if i < len(n.args):
                        self._check_key(n.args[i], n, qual, label, facts,
                                        mod, flag, where="static arg")

    # -- component rules ---------------------------------------------------
    def _check_key(self, key_expr, anchor, qual, label, facts, mod, flag,
                   where="cache key"):
        seen = set()
        for comp in _components(key_expr):
            if _through_bucket(comp):
                continue
            code, tok = self._classify(comp, facts)
            if code is None or (code, tok) in seen:
                continue
            seen.add((code, tok))
            text = ast.unparse(comp)[:50]
            if code == "unbucketed-shape-key":
                msg = (f"{where} component `{text}` is a data-dependent "
                       "dynamic extent — one compile per request shape "
                       "(the compile_retrace sentinel fires at runtime; "
                       "this is the same bug before it ships).  Bucket "
                       "the extent (cf. ServingEngine._bucket_for) or "
                       "pragma with the documented per-shape contract")
            elif code == "float-cache-key":
                msg = (f"{where} component `{text}` is a computed float "
                       "— any jitter retraces; canonicalize to a "
                       "bounded knob or quantize before keying")
            else:
                msg = (f"{where} component `{text}` iterates a dict/set "
                       "— hash order varies run-to-run, so the key "
                       "never matches a warm cache; wrap in sorted()")
            flag(anchor, qual, code,
                 f"[surface={label}] {msg}", f"{label}:{tok}")

    def _classify(self, comp, facts):
        # unordered: set/dict-view iteration not wrapped in sorted()
        for c in ast.walk(comp):
            if isinstance(c, ast.Call) and isinstance(c.func, ast.Name) \
                    and c.func.id == "sorted":
                break
        else:
            for c in ast.walk(comp):
                if isinstance(c, (ast.Set, ast.SetComp)):
                    return "unordered-key-part", "set"
                if isinstance(c, ast.Call):
                    if isinstance(c.func, ast.Name) and \
                            c.func.id in ("set", "frozenset"):
                        return "unordered-key-part", c.func.id
                    if isinstance(c.func, ast.Attribute) and \
                            c.func.attr in ("keys", "values", "items"):
                        return "unordered-key-part", c.func.attr
        # shape: data-derived extents
        if facts._shape_extract(comp):
            return "unbucketed-shape-key", "shape"
        for c in ast.walk(comp):
            if isinstance(c, ast.Name) and c.id in facts.shapeish:
                return "unbucketed-shape-key", c.id
            if isinstance(c, ast.Call):
                name = (dotted(c.func) or "").lower()
                leaf = name.rsplit(".", 1)[-1]
                if any(f in leaf for f in _SHAPEY_CALL_FRAGMENTS) and \
                        any(isinstance(a, ast.Name) and
                            (a.id in facts.data or a.id in facts.shapeish)
                            for a in c.args):
                    return "unbucketed-shape-key", leaf
        # computed floats
        for c in ast.walk(comp):
            if isinstance(c, ast.Call) and isinstance(c.func, ast.Name) \
                    and c.func.id == "float" and c.args:
                arg = c.args[0]
                plain = True
                for x in ast.walk(arg):
                    if isinstance(x, ast.Call):
                        plain = False
                    if isinstance(x, ast.Name) and (
                            x.id in facts.assigns or
                            x.id in facts.shapeish):
                        plain = False
                if not plain:
                    return "float-cache-key", "float"
        return None, None
