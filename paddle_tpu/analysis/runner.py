"""Unified runner for the static-analysis passes.

``python -m paddle_tpu.analysis`` (or ``python tools/lint.py``) runs all
passes over the repo; ``--json`` emits machine-readable findings; the
committed baseline (``tools/lint_baseline.json``) suppresses
pre-existing findings so only NEW ones fail the run (exit 1).  Update
the baseline deliberately with ``--update-baseline`` — a growing
baseline is a growing debt, and the diff shows it.
"""
import argparse
import json
import os
import sys

from .base import Finding, ProjectIndex, collect_py_files, \
    collect_text_files

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join("tools", "lint_baseline.json")


def _passes():
    # imported lazily so `from paddle_tpu.analysis import jit_surface`
    # stays free of the pass machinery
    from .tracer_safety import TracerSafetyPass
    from .host_sync import HostSyncPass
    from .collective_order import CollectiveOrderPass
    from .donation import DonationPass
    from .retrace_hazard import RetraceHazardPass
    from .concurrency import ConcurrencyPass
    from .mesh_axes import MeshAxesPass
    from .dtype_flow import DtypeFlowPass
    from .spec_drift import SpecDriftPass
    from .registry_lints import (FailpointRefsPass, GuardianLogSchemaPass,
                                 MetricNamesPass)
    return {p.name: p for p in (TracerSafetyPass, HostSyncPass,
                                CollectiveOrderPass, DonationPass,
                                RetraceHazardPass, ConcurrencyPass,
                                MeshAxesPass, DtypeFlowPass,
                                SpecDriftPass,
                                FailpointRefsPass, GuardianLogSchemaPass,
                                MetricNamesPass)}


def _optional_passes():
    """Passes that run ONLY when named in --passes (never in the
    default all-passes sweep): the bench trajectory gate depends on
    committed BENCH artifacts and machine-load-sensitive numbers, so
    it belongs in the bench workflow, opted into explicitly."""
    from .bench_gate import BenchComparePass
    return {p.name: p for p in (BenchComparePass,)}


class Context:
    """What a pass sees: the parsed code index plus the reference files
    (tests/docs) the registry lints scan."""

    def __init__(self, root, py_files, ref_files, default_tree):
        self.root = root
        self.py_files = py_files
        self.ref_files = ref_files
        self.default_tree = default_tree
        self._index = None

    @property
    def index(self):
        if self._index is None:
            self._index = ProjectIndex(self.root, self.py_files)
        return self._index


def make_context(paths=None, root=None):
    if paths:
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise ValueError(f"path(s) do not exist: {missing}")
        py = collect_py_files(paths)
        ref = collect_text_files(paths)
        if not py and not ref:
            raise ValueError(
                f"no .py/.md files found under {list(paths)} — a typo'd "
                "path must not report a green lint")
        # in-repo scoped runs keep the registry lints' reference scope
        # identical to the default run (tests/ + docs/): package source
        # is analyzed code, not a reference corpus — a docstring example
        # must not fail a scoped run that the full run passes
        def _is_ref(f):
            rel = os.path.relpath(os.path.abspath(f), REPO_ROOT)
            return rel.replace(os.sep, "/").startswith(("tests/", "docs/"))
        if all(os.path.commonpath([REPO_ROOT, os.path.abspath(p)])
               == REPO_ROOT for p in paths):
            ref = [f for f in ref if _is_ref(f)]
        if root is None:
            # paths inside the repo keep repo-rooted relpaths so the
            # relpath-keyed policy (monitored modules, EXTRA surfaces,
            # baseline keys) applies identically to partial runs;
            # out-of-tree fixtures root at their common parent
            absolute = [os.path.abspath(p) for p in paths]
            if all(os.path.commonpath([REPO_ROOT, a]) == REPO_ROOT
                   for a in absolute):
                root = REPO_ROOT
            else:
                dirs = [a if os.path.isdir(a) else os.path.dirname(a) or "."
                        for a in absolute]
                root = os.path.commonpath(dirs)
        return Context(os.path.abspath(root), py, ref, default_tree=False)
    root = os.path.abspath(root or REPO_ROOT)
    py = collect_py_files([os.path.join(root, "paddle_tpu")])
    ref = collect_text_files([os.path.join(root, "tests"),
                              os.path.join(root, "docs")])
    return Context(root, py, ref, default_tree=True)


def run_passes(paths=None, passes=None, root=None, ctx=None,
               timings=None):
    """Run the selected passes; returns a deterministically-ordered
    Finding list (parse failures included as `parse` findings).  Pass
    a dict as ``timings`` to collect per-pass wall seconds plus the
    ``"total"`` (the sweep shares one parsed-module cache across
    passes, and ``--json`` reports the resulting wall time)."""
    import time
    ctx = ctx or make_context(paths, root)
    registry = _passes()
    if passes:
        # opt-in passes join the registry only when explicitly named
        optional = _optional_passes()
        registry.update({n: p for n, p in optional.items()
                         if n in passes})
    names = list(registry) if not passes else list(passes)
    unknown = [n for n in names if n not in registry]
    if unknown:
        known = sorted(set(_passes()) | set(_optional_passes()))
        raise ValueError(f"unknown pass(es) {unknown}; known: {known}")
    findings = []
    ast_passes = {"tracer-safety", "host-sync", "collective-order",
                  "donation", "retrace-hazard", "concurrency",
                  "mesh-axes", "dtype-flow", "spec-drift"}
    t_total = time.perf_counter()
    if any(n in ast_passes for n in names):
        for rel, msg in ctx.index.errors:
            findings.append(Finding("parse", rel, 1, "<module>",
                                    "syntax-error", msg, "syntax"))
    for name in names:
        t0 = time.perf_counter()
        findings.extend(registry[name]().run(ctx))
        if timings is not None:
            timings[name] = round(time.perf_counter() - t0, 4)
    if timings is not None:
        timings["total"] = round(time.perf_counter() - t_total, 4)
    return sorted(findings, key=Finding.sort_key)


# -- baseline --------------------------------------------------------------

def load_baseline(path):
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("findings", {}))


def write_baseline(path, findings):
    counts = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    data = {"version": 1,
            "comment": "pre-existing lint findings suppressed by "
                       "paddle_tpu.analysis; shrink me, don't grow me "
                       "(--update-baseline)",
            "findings": {k: counts[k] for k in sorted(counts)}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def split_new(findings, baseline_counts):
    """Partition findings into (new, baselined) against baseline key
    counts — the first N occurrences of a key are baselined, the rest
    are new."""
    seen = {}
    new, old = [], []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        if seen[k] <= baseline_counts.get(k, 0):
            old.append(f)
        else:
            new.append(f)
    return new, old


# -- changed-only scoping --------------------------------------------------

def git_changed_files(root):
    """Repo files changed vs HEAD (staged + unstaged) plus untracked,
    filtered to the extensions the passes read and to files that still
    exist.  Used by ``--changed-only`` so the inner loop lints the diff
    while CI stays exhaustive."""
    import subprocess
    out = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"--changed-only needs git: {e}")
        if res.returncode != 0:
            raise RuntimeError(
                f"--changed-only: `{' '.join(cmd)}` failed: "
                f"{res.stderr.strip()}")
        out.extend(res.stdout.splitlines())
    files = []
    for rel in sorted(set(out)):
        if not rel.endswith((".py", ".md")):
            continue
        path = os.path.join(root, rel)
        if os.path.exists(path):          # deleted files have no AST
            files.append(path)
    return files


# -- CLI -------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Static-analysis suite: tracer-safety, host-sync "
                    "budget, collective-order and registry lints.")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the repo's "
                         "paddle_tpu/ + tests/ + docs/)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (see --list-passes)")
    ap.add_argument("--list-passes", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/lint_baseline.json "
                         "for full-tree runs)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: all findings are new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "and exit 0")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs git HEAD (plus "
                         "untracked) — the inner-loop mode; CI runs "
                         "the full sweep")
    args = ap.parse_args(argv)

    if args.list_passes:
        for name in _passes():
            print(name)
        for name in _optional_passes():
            print(f"{name} (opt-in: runs only when named in --passes)")
        return 0

    passes = [p.strip() for p in args.passes.split(",")] \
        if args.passes else None
    paths = args.paths or None
    if args.changed_only:
        if paths:
            print("error: --changed-only and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        if args.update_baseline:
            print("error: --update-baseline needs the full default "
                  "tree, not a --changed-only subset", file=sys.stderr)
            return 2
        try:
            paths = git_changed_files(REPO_ROOT)
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not paths:
            print("OK: no changed .py/.md files vs HEAD "
                  "(--changed-only)")
            return 0
    timings = {}
    try:
        ctx = make_context(paths)
        findings = run_passes(passes=passes, ctx=ctx, timings=timings)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and ctx.root == REPO_ROOT:
        # in-repo runs (full tree OR explicit repo paths) share the
        # committed baseline — relpaths are repo-rooted either way, so
        # a partial run must not re-fail already-baselined findings
        baseline_path = os.path.join(ctx.root, DEFAULT_BASELINE)
    if args.update_baseline:
        if not baseline_path or \
                (not ctx.default_tree and args.baseline is None) or \
                (passes is not None and args.baseline is None):
            # a partial run (path subset OR pass subset) must never
            # overwrite the shared baseline — it would erase every
            # finding outside its scope
            print("error: --update-baseline needs the full default tree "
                  "with all passes, or an explicit --baseline",
                  file=sys.stderr)
            return 2
        write_baseline(baseline_path, findings)
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{os.path.relpath(baseline_path, ctx.root)}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, old = split_new(findings, baseline)

    if args.as_json:
        new_ids = {id(f) for f in new}
        out = {"total": len(findings), "new": len(new),
               "baselined": len(old),
               "wall_time_s": timings,
               "findings": [dict(f.to_dict(), new=(id(f) in new_ids))
                            for f in findings]}
        print(json.dumps(out, indent=1, sort_keys=True))
        return 1 if new else 0

    for f in new:
        print(f"NEW {f!r}")
    if old:
        print(f"({len(old)} baselined finding(s) suppressed; "
              "see tools/lint_baseline.json)")
    ran = ",".join(passes) if passes else "all passes"
    if new:
        print(f"FAIL: {len(new)} new finding(s) ({ran}); fix them, "
              "`# lint: allow(<code>)` a justified one, or "
              "--update-baseline deliberately")
        return 1
    print(f"OK: no new findings ({ran}, {len(findings)} total, "
          f"{len(old)} baselined, {timings.get('total', 0.0):.2f}s)")
    return 0
