"""Cross-artifact drift pass: the PR 3 registry-lint discipline
generalized to the sharding/numerics vocabularies.

Three artifact pairs are held in lockstep:

1. ``MESH_AXES`` (allowlist.py) vs the tree's actual mesh construction
   sites — a ``jax.sharding.Mesh(..., (names))`` literal naming an
   undeclared axis is ``mesh-axis-undeclared``; a vocabulary entry no
   construction/spec/collective site uses is ``mesh-axis-unused``
   (dead vocabulary reads as coverage that isn't there).
2. ``COMPILE_SURFACES`` (allowlist.py) vs the ``compilestats.wrap``
   literals and ``*_SURFACE`` constants in source — the static-finding
   labels and the runtime ``pt_compile_*`` labels must stay one
   vocabulary (``surface-drift``; the test_graph_discipline assertion,
   now enforced at lint time).
3. ``docs/DISTRIBUTED.md`` vs the code it documents: backticked repo
   paths must exist (``stale-doc-ref``), the ``grad_comm_configs``
   block's keys must be real ``GradCommConfig`` parameters
   (``grad-comm-drift``), and the documented wire modes must mirror
   ``_QUANT_MODES`` (``wire-mode-drift``) — checked row-for-row like
   the watch-rule/metric tables.

Pure AST + text: the pass imports nothing from the analyzed tree, so
it runs on fixtures and broken trees (the doc checks scope to any
in-scope ``DISTRIBUTED.md``; the vocabulary-completeness directions
run only on the default full-tree sweep, where absence is meaningful).
"""
import ast
import os
import re

from .base import Finding, call_terminal, read_text, WRAP_CALLEES
from .allowlist import MESH_AXES, COMPILE_SURFACES
from .mesh_axes import (COLLECTIVE_AXIS_ARG, _axis_literals,
                        _collective_axis_arg, _is_pspec_call,
                        _SHARD_MAP_CALLEES)

PASS_NAME = "spec-drift"

ALLOWLIST_PATH = "paddle_tpu/analysis/allowlist.py"
DISTRIBUTED_DOC = "docs/DISTRIBUTED.md"

# backticked repo-relative path references in the distributed guide
_DOC_PATH_RE = re.compile(
    r"`((?:tests|docs|tools|ops|paddle_tpu)/[A-Za-z0-9_/.-]+?"
    r"\.(?:py|md|json))`")
# the grad_comm_configs example block and its keys
_CFG_BLOCK_RE = re.compile(r"grad_comm_configs\s*=\s*\{(.*?)\}", re.S)
_CFG_KEY_RE = re.compile(r"\"(\w+)\"\s*:")
# documented wire modes: backticked quoted tokens in the grad_comm
# section bullets
_WIRE_MODE_RE = re.compile(r"`\"([a-z0-9_]+)\"`")
_GRAD_COMM_SECTION_RE = re.compile(
    r"^##[^\n]*gradient reduction[^\n]*$", re.I | re.M)


def _line_of(text, pos):
    return text.count("\n", 0, pos) + 1


class SpecDriftPass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        self._check_mesh_vocabulary(ctx, findings)
        if ctx.default_tree:
            self._check_surfaces(ctx, findings)
        for doc in self._docs_in_scope(ctx):
            findings.extend(self._check_doc(ctx, doc))
        return sorted(findings, key=Finding.sort_key)

    # -- 1. MESH_AXES vs construction sites ----------------------------------
    def _check_mesh_vocabulary(self, ctx, findings):
        used = set()
        for mod in ctx.index.iter_modules():
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.FunctionDef) or \
                        isinstance(n, ast.AsyncFunctionDef):
                    # axis-naming parameter defaults are usage sites
                    a = n.args
                    for p, d in zip((a.posonlyargs + a.args)
                                    [-len(a.defaults):] if a.defaults
                                    else [], a.defaults):
                        if (p.arg == "axis" or p.arg == "axis_name" or
                                p.arg.endswith("_axis")) and \
                                isinstance(d, ast.Constant) and \
                                isinstance(d.value, str):
                            used.add(d.value)
                if not isinstance(n, ast.Call):
                    continue
                term = call_terminal(n.func)
                if term == "Mesh":
                    names = None
                    if len(n.args) > 1:
                        names = n.args[1]
                    for kw in n.keywords:
                        if kw.arg == "axis_names":
                            names = kw.value
                    if isinstance(names, (ast.Tuple, ast.List)):
                        for name, node in _axis_literals(names):
                            used.add(name)
                            if name not in MESH_AXES and not (
                                    {self.name, "mesh-axis-undeclared"}
                                    & mod.allowed_on_line(node.lineno)):
                                findings.append(Finding(
                                    self.name, mod.relpath, node.lineno,
                                    "<mesh>", "mesh-axis-undeclared",
                                    f"Mesh construction names axis "
                                    f"{name!r} which is not in the "
                                    "MESH_AXES vocabulary "
                                    f"({ALLOWLIST_PATH}) — every "
                                    "framework-owned mesh axis must be "
                                    "declared so specs and collectives "
                                    "are checkable against it", name))
                elif _is_pspec_call(n, mod) or \
                        term in _SHARD_MAP_CALLEES:
                    for name, _ in _axis_literals(n):
                        used.add(name)
                elif term in COLLECTIVE_AXIS_ARG:
                    expr = _collective_axis_arg(n)
                    if expr is not None:
                        for name, _ in _axis_literals(expr):
                            used.add(name)
        if ctx.default_tree:
            for ax in MESH_AXES:
                if ax not in used:
                    findings.append(Finding(
                        self.name, ALLOWLIST_PATH, 1, "<vocabulary>",
                        "mesh-axis-unused",
                        f"MESH_AXES declares axis {ax!r} but no mesh "
                        "construction, PartitionSpec, shard_map spec "
                        "or collective in the tree uses it — dead "
                        "vocabulary reads as sharding coverage that "
                        "isn't there; drop the entry or land the axis",
                        ax))

    # -- 2. COMPILE_SURFACES vs wrap literals --------------------------------
    def _check_surfaces(self, ctx, findings):
        in_tree = {}          # label -> (relpath, line)
        for mod in ctx.index.iter_modules():
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.Call) and \
                        call_terminal(n.func) in WRAP_CALLEES:
                    # walk the label argument's subtree: labels can be
                    # conditional ("a.b" if flag else "a.c")
                    for a in n.args:
                        for c in ast.walk(a):
                            if isinstance(c, ast.Constant) and \
                                    isinstance(c.value, str) and \
                                    "." in c.value:
                                in_tree.setdefault(c.value,
                                                   (mod.relpath, n.lineno))
                elif isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Name) and \
                                t.id.endswith("_SURFACE") and \
                                isinstance(n.value, ast.Constant) and \
                                isinstance(n.value.value, str):
                            in_tree.setdefault(n.value.value,
                                               (mod.relpath, n.lineno))
        declared = set(COMPILE_SURFACES)
        for label in sorted(set(in_tree) - declared):
            rel, line = in_tree[label]
            findings.append(Finding(
                self.name, rel, line, "<surface>", "surface-drift",
                f"compile surface {label!r} is wrapped in source but "
                f"missing from COMPILE_SURFACES ({ALLOWLIST_PATH}) — "
                "retrace-hazard findings and pt_compile_* metrics must "
                "share one label vocabulary; declare it", label))
        for label in sorted(declared - set(in_tree)):
            findings.append(Finding(
                self.name, ALLOWLIST_PATH, 1, "<vocabulary>",
                "surface-drift",
                f"COMPILE_SURFACES declares {label!r} but no "
                "compilestats wrap literal or *_SURFACE constant in "
                "the tree carries it — a stale label means dashboards "
                "watch a surface that no longer reports; drop or "
                "rewire it", label))

    # -- 3. docs/DISTRIBUTED.md ----------------------------------------------
    def _docs_in_scope(self, ctx):
        docs = []
        for p in ctx.ref_files:
            if os.path.basename(p) == os.path.basename(DISTRIBUTED_DOC):
                docs.append(p)
        default = os.path.join(ctx.root, DISTRIBUTED_DOC)
        if ctx.default_tree and os.path.exists(default) and \
                not any(os.path.abspath(p) == os.path.abspath(default)
                        for p in docs):
            docs.append(default)
        return docs

    def _check_doc(self, ctx, doc):
        findings = []
        rel = os.path.relpath(doc, ctx.root).replace(os.sep, "/")
        text = read_text(doc)
        for m in _DOC_PATH_RE.finditer(text):
            ref = m.group(1)
            if not os.path.exists(os.path.join(ctx.root, ref)):
                findings.append(Finding(
                    self.name, rel, _line_of(text, m.start()), "<doc>",
                    "stale-doc-ref",
                    f"references `{ref}` which does not exist — a "
                    "moved/renamed file leaves the guide pointing at "
                    "nothing; fix the path", ref))
        gc = self._grad_comm_module(ctx)
        cfg = _CFG_BLOCK_RE.search(text)
        if cfg is not None and gc is not None:
            params = self._config_params(gc)
            doc_keys = {m2.group(1): cfg.start(1) + m2.start()
                        for m2 in _CFG_KEY_RE.finditer(cfg.group(1))}
            for key, pos in sorted(doc_keys.items()):
                if params and key not in params:
                    findings.append(Finding(
                        self.name, rel, _line_of(text, pos), "<doc>",
                        "grad-comm-drift",
                        f"grad_comm_configs documents key {key!r} but "
                        "GradCommConfig takes no such parameter — the "
                        "example silently misconfigures; fix the key",
                        key))
            for p in sorted(params - set(doc_keys) - {"enabled"}):
                findings.append(Finding(
                    self.name, rel, _line_of(text, cfg.start()), "<doc>",
                    "grad-comm-drift",
                    f"GradCommConfig parameter {p!r} is missing from "
                    "the documented grad_comm_configs block — an "
                    "undocumented knob doesn't exist for users; add "
                    "the row", p))
        if gc is not None:
            sec = _GRAD_COMM_SECTION_RE.search(text)
            if sec is not None:
                start = sec.end()
                nxt = text.find("\n## ", start)
                section = text[start:nxt if nxt != -1 else len(text)]
                doc_modes = set(_WIRE_MODE_RE.findall(section))
                code_modes = self._quant_modes(gc)
                if doc_modes and code_modes:
                    for mmode in sorted(doc_modes - code_modes):
                        findings.append(Finding(
                            self.name, rel,
                            _line_of(text, start), "<doc>",
                            "wire-mode-drift",
                            f"documents wire mode {mmode!r} which "
                            "_QUANT_MODES does not accept — the "
                            "config example raises at runtime; fix "
                            "the mode list", mmode))
                    for mmode in sorted(code_modes - doc_modes):
                        findings.append(Finding(
                            self.name, rel,
                            _line_of(text, start), "<doc>",
                            "wire-mode-drift",
                            f"wire mode {mmode!r} is accepted by "
                            "_QUANT_MODES but undocumented in the "
                            "grad_comm section — document the "
                            "accuracy contract or drop the mode",
                            mmode))
        return findings

    @staticmethod
    def _grad_comm_module(ctx):
        for mod in ctx.index.iter_modules():
            if mod.relpath.endswith("grad_comm.py"):
                return mod
        return None

    @staticmethod
    def _config_params(mod):
        fi = mod.funcs.get("GradCommConfig.__init__")
        if fi is None:
            return set()
        a = fi.node.args
        return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
                if p.arg not in ("self", "cls")}

    @staticmethod
    def _quant_modes(mod):
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name) and \
                            t.id == "_QUANT_MODES" and \
                            isinstance(n.value, (ast.Tuple, ast.List)):
                        return {e.value for e in n.value.elts
                                if isinstance(e, ast.Constant) and
                                isinstance(e.value, str)}
        return set()
