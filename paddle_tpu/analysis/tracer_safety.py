"""Tracer-safety pass: walk functions reachable from registered jit
surfaces and flag trace-breaking patterns.

A jitted function sees *tracers*, not values; any construct that needs a
concrete value — ``float(x)``/``int(x)``/``bool(x)``, ``len(x)``,
``.item()``/``.numpy()``, ``np.asarray(x)``, or a Python ``if``/``while``
on a tensor expression — either crashes at trace time
(ConcretizationTypeError) or, worse, silently bakes one traced branch
into the compiled program.  This pass finds them statically.

Mechanics:

- Surfaces: functions carrying the ``@analysis.jit_surface`` decorator
  (found syntactically, so fixture files work un-imported) plus the
  nested functions listed in ``allowlist.EXTRA_JIT_SURFACES``.
- Reachability: best-effort static call graph (same-module names,
  ``self.`` methods, imported-module attributes).  Dynamic calls
  (``self.network(...)``) stop the walk — deliberately conservative, so
  the pass stays fast and quiet.
- Taint: parameters of surfaces (and their nested defs — the actual
  traced bodies built by stepper builders) are traced values; results
  of ``jnp.*``/``jax.*``/``lax.*`` calls are traced; assignments
  propagate.  Metadata reads (``.shape``/``.dtype``, ``issubdtype``)
  and identity/membership tests (``is None``, ``k in cache``) are
  trace-time-static and exempt.
"""
import ast

from .base import Finding, call_terminal, dotted, assign_names, \
    param_names
from .allowlist import EXTRA_JIT_SURFACES, STATIC_FUNCS, STATIC_ATTRS

PASS_NAME = "tracer-safety"

_CASTS = ("float", "int", "bool", "complex")
_READBACKS = ("item", "numpy", "tolist", "block_until_ready")


def _local_walk(fnode):
    """Walk a function body without descending into nested defs (they
    are analyzed as their own functions, with their own taint scope)."""
    stack = list(fnode.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _is_array_ns_call(call, mod):
    """True for calls into the jax/jnp/lax namespaces (array-producing
    under trace), excluding the static metadata helpers."""
    name = dotted(call.func)
    if not name:
        return False
    root = name.split(".", 1)[0]
    target = mod.alias_module(root) or root
    if not (target == "jax" or target.startswith("jax.")):
        return False
    return name.split(".")[-1] not in STATIC_FUNCS


def _is_numpy_ns_call(call, mod):
    name = dotted(call.func)
    if not name:
        return False
    root = name.split(".", 1)[0]
    target = mod.alias_module(root) or root
    return target == "numpy" or target.startswith("numpy.")


def _expr_tainted(expr, tainted, mod, containers=frozenset()):
    """Does this expression (transitively) mention a traced value?

    ``containers`` holds names bound to *python containers of* traced
    values (``dict(zip(idx, traced))``): membership over their keys is
    host-static, but membership over a traced array itself
    (``3 in xs``) calls the tracer's ``__contains__`` and crashes."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            continue                      # metadata: static under trace
        if isinstance(n, ast.Call):
            term = call_terminal(n.func)
            if term in STATIC_FUNCS:
                continue                  # issubdtype & co: static verdicts
            if _is_array_ns_call(n, mod):
                return True
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in tainted:
            return True
        if isinstance(n, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                continue                  # identity: host-static
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in n.ops):
                stack.append(n.left)
                # keys of a container-of-traced are static; a traced
                # array as the container is not
                for c in n.comparators:
                    if _expr_tainted(c, tainted - containers, mod,
                                     containers):
                        return True
                continue
        stack.extend(ast.iter_child_nodes(n))
    return False


_CONTAINER_CTORS = ("dict", "list", "set", "tuple", "frozenset")


def _compute_taint(fnode, mod, taint_params):
    """Returns (tainted names, container-of-traced names)."""
    tainted = set(param_names(fnode)) if taint_params else set()
    containers = set()
    for _ in range(3):                     # small fixpoint: 3 rounds cover
        before = len(tainted)              # realistic chain depths
        for n in _local_walk(fnode):
            if isinstance(n, ast.Assign):
                if _expr_tainted(n.value, tainted, mod):
                    for t in n.targets:
                        tainted.update(assign_names(t))
                    v = n.value
                    if isinstance(v, ast.Call) and \
                            isinstance(v.func, ast.Name) and \
                            v.func.id in _CONTAINER_CTORS:
                        for t in n.targets:
                            containers.update(assign_names(t))
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                if n.value is not None and \
                        _expr_tainted(n.value, tainted, mod):
                    tainted.update(assign_names(n.target))
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                if _expr_tainted(n.iter, tainted, mod):
                    it = n.iter
                    fname = it.func.id if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Name) else None
                    if fname == "range":
                        pass            # range() yields host ints
                    elif fname == "enumerate" and \
                            isinstance(n.target, ast.Tuple) and \
                            len(n.target.elts) == 2:
                        # the index is a host int; only the element is
                        # traced
                        tainted.update(assign_names(n.target.elts[1]))
                    else:
                        tainted.update(assign_names(n.target))
            elif isinstance(n, ast.NamedExpr):
                if _expr_tainted(n.value, tainted, mod):
                    tainted.update(assign_names(n.target))
        if len(tainted) == before:
            break
    return tainted, containers


class TracerSafetyPass:
    name = PASS_NAME

    def run(self, ctx):
        index = ctx.index
        findings = []
        # -- surface set ---------------------------------------------------
        work = []                          # (FuncInfo, taint_params)
        for mod in index.iter_modules():
            for qual in sorted(mod.funcs):
                if mod.funcs[qual].is_surface:
                    work.append((mod.funcs[qual], True))
        for rel, qual in EXTRA_JIT_SURFACES:
            for mod in index.iter_modules():
                if mod.relpath == rel or mod.relpath.endswith("/" + rel):
                    fi = mod.funcs.get(qual)
                    if fi is not None:
                        work.append((fi, True))
                    else:
                        # a renamed nested def must not silently drop
                        # its lint coverage — an unresolvable entry is
                        # itself a finding
                        findings.append(Finding(
                            self.name, mod.relpath, 1, qual,
                            "unresolved-surface",
                            f"EXTRA_JIT_SURFACES names `{qual}` but no "
                            "such function exists in this file — the "
                            "surface was renamed or removed and is no "
                            "longer analyzed; update "
                            "paddle_tpu/analysis/allowlist.py (and the "
                            "register_jit_surface call)", qual))
        # -- reachability walk --------------------------------------------
        done = {}                          # id(FuncInfo) -> taint flag
        while work:
            fi, taint_params = work.pop(0)
            prev = done.get(id(fi))
            if prev is not None and (prev or not taint_params):
                continue                   # already done at >= this level
            done[id(fi)] = taint_params
            self._analyze(fi, taint_params, index, findings, work)
        # findings can repeat when a function is re-analyzed with
        # upgraded taint — dedupe on full identity
        uniq = {}
        for f in findings:
            uniq[(f.path, f.line, f.code, f.detail, f.message)] = f
        return sorted(uniq.values(), key=Finding.sort_key)

    # -- per-function analysis --------------------------------------------
    def _analyze(self, fi, taint_params, index, findings, work):
        mod = fi.module
        fnode = fi.node
        tainted, containers = _compute_taint(fnode, mod, taint_params)

        def flag(node, code, message, detail):
            if {self.name, code} & mod.allowed_on_line(node.lineno):
                return
            findings.append(Finding(
                self.name, mod.relpath, node.lineno, fi.qualname, code,
                message, detail))

        # nested defs are the traced bodies the builders return — they
        # inherit the surface's taint discipline
        prefix = fi.qualname + "."
        for qual in sorted(mod.funcs):
            if qual.startswith(prefix) and "." not in qual[len(prefix):]:
                work.append((mod.funcs[qual], taint_params))

        for n in _local_walk(fnode):
            if isinstance(n, ast.Call):
                self._check_call(n, fi, mod, tainted, containers, flag)
                callee = index.resolve_call(mod, fi.qualname, n.func)
                if callee is not None:
                    work.append((callee, False))
            elif isinstance(n, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                if _expr_tainted(n.test, tainted, mod, containers):
                    kind = {ast.If: "if", ast.While: "while",
                            ast.IfExp: "if-expression",
                            ast.Assert: "assert"}[type(n)]
                    flag(n, "control-flow-on-traced",
                         f"Python `{kind}` on a traced tensor expression "
                         f"(`{ast.unparse(n.test)[:60]}`) — under jit this "
                         "needs a concrete value: use lax.cond/jnp.where "
                         "(or checkify for asserts), or hoist the "
                         "decision to trace time",
                         f"{kind}:{ast.unparse(n.test)[:40]}")

    def _check_call(self, n, fi, mod, tainted, containers, flag):
        args = list(n.args) + [kw.value for kw in n.keywords]
        term = call_terminal(n.func)
        if isinstance(n.func, ast.Name) and n.func.id in _CASTS:
            if any(_expr_tainted(a, tainted, mod, containers)
                   for a in args):
                flag(n, "cast-on-traced",
                     f"`{n.func.id}()` on a traced value forces a host "
                     "sync / ConcretizationTypeError under jit — keep the "
                     "verdict on device (jnp.where/lax.cond) or read it "
                     "back once through guardian._host_bool outside the "
                     "trace", n.func.id)
            return
        if isinstance(n.func, ast.Name) and n.func.id == "len":
            if any(_expr_tainted(a, tainted, mod, containers)
                   for a in args):
                flag(n, "len-on-traced",
                     "`len()` on a possibly-traced array — use "
                     "`x.shape[0]` (static under trace)", "len")
            return
        if isinstance(n.func, ast.Attribute) and term in _READBACKS \
                and not args:
            flag(n, "host-readback",
                 f"`.{term}()` is a device->host readback — illegal "
                 "inside a jitted path (and a hidden sync anywhere on "
                 "the step path)", term)
            return
        if term == "device_get":
            flag(n, "host-readback",
                 "`device_get` inside jit-reachable code is a host "
                 "readback", term)
            return
        if term == "_host_bool":
            flag(n, "host-sync-in-trace",
                 "guardian._host_bool is THE counted host sync — it must "
                 "run outside the traced step, on the returned flag",
                 term)
            return
        if _is_numpy_ns_call(n, mod):
            if any(_expr_tainted(a, tainted, mod, containers)
                   for a in args):
                flag(n, "numpy-on-traced",
                     f"`{dotted(n.func)}` on a traced value materializes "
                     "it on host (breaks tracing; silent sync in eager) — "
                     "use the jnp equivalent", dotted(n.func) or "np")
