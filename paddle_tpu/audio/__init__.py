"""paddle.audio (reference: python/paddle/audio/ — features
(Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC layers), functional
(mel scale, fbank matrix, dct), backends).

TPU-native: features are Layers over paddle.signal's XLA STFT plus one
fbank/DCT matmul (MXU); the mel/DCT matrices are precomputed numpy
constants (host-side, trace-free).
"""
from . import functional  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from .backends import load, save, info  # noqa: F401

__all__ = ["functional", "features", "backends", "load", "save", "info"]
