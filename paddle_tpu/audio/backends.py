"""paddle.audio.backends (reference: python/paddle/audio/backends —
soundfile-backed load/save/info with a pluggable backend registry).

Offline environment: no soundfile/librosa, so the built-in backend is
the stdlib ``wave`` module (PCM WAV, 16/24/32-bit int + float via
scaling).  The registry API is kept so a soundfile backend can be
registered when available.
"""
import wave as _wave

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["get_current_audio_backend", "list_available_backends",
           "set_backend", "load", "save", "info", "AudioInfo"]

_BACKEND = ["wave"]


def list_available_backends():
    return ["wave"]


def get_current_audio_backend():
    return _BACKEND[0]


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise ValueError(
            f"unknown audio backend {backend_name!r}; available: "
            f"{list_available_backends()} (soundfile is not installed "
            "in this environment)")
    _BACKEND[0] = backend_name


class AudioInfo:
    """reference: paddle.audio.backends AudioInfo record."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def info(filepath, format=None):
    with _wave.open(filepath, "rb") as f:
        width = f.getsampwidth()
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), width * 8,
                         encoding="PCM_U" if width == 1 else "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True, format=None):
    """WAV -> (Tensor, sample_rate).  ``normalize`` scales ints to
    [-1, 1] float32 (the reference default)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n_ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 2:
        raw_i = np.frombuffer(raw, dtype="<i2")
        scale = 32768.0
    elif width == 4:
        raw_i = np.frombuffer(raw, dtype="<i4")
        scale = 2147483648.0
    elif width == 1:
        raw_i = np.frombuffer(raw, dtype=np.uint8)
        scale = 128.0
    elif width == 3:
        b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3)
        v = ((b[:, 0].astype(np.int32))
             | (b[:, 1].astype(np.int32) << 8)
             | (b[:, 2].astype(np.int32) << 16))
        raw_i = np.where(v >= 1 << 23, v - (1 << 24), v).astype(np.int32)
        scale = float(1 << 23)
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if normalize:
        flt = raw_i.astype(np.float32)
        if width == 1:
            flt = flt - 128.0
        data = (flt / scale).reshape(-1, n_ch)
    else:
        # native integer dtype, like the reference backends
        data = raw_i.reshape(-1, n_ch)
    out = data.T if channels_first else data
    return Tensor(jnp.asarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16, format=None, encoding=None):
    """(Tensor|(C, T)/(T, C) array) -> PCM WAV."""
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        # 1-D waveform: one channel regardless of channels_first
        arr = arr[None, :]
    elif not channels_first:
        arr = arr.T
    if bits_per_sample != 16:
        raise NotImplementedError("save: 16-bit PCM only")
    if np.issubdtype(arr.dtype, np.floating):
        pcm = np.clip(np.round(arr * 32767.0), -32768, 32767) \
            .astype("<i2")
    else:
        # clip out-of-range ints instead of silently wrapping mod 2^16
        pcm = np.clip(arr, -32768, 32767).astype("<i2")
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[0])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.T.tobytes())
