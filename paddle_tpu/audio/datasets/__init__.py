"""paddle.audio.datasets (reference: python/paddle/audio/datasets/ —
TESS, ESC50 download-and-extract datasets).

Zero-egress environment: like the text/vision datasets here, these are
deterministic synthetic stand-ins with the REFERENCE's shapes, label
spaces, and feature modes — training pipelines exercise the identical
surface (waveform/spectrogram/logmel features via audio.features), and
a user pointing `archive_path` at the real extracted archives gets the
real data.
"""
import os

import numpy as np

from ...io import Dataset
from ..features import LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["TESS", "ESC50"]


class _SyntheticAudioDataset(Dataset):
    N_PER_CLASS = 8
    SR = 16000
    DUR = 1.0

    def __init__(self, mode="train", feat_type="raw", seed=0, **feat_kw):
        self.mode = mode
        self.feat_type = feat_type
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        n = self.N_PER_CLASS * self.n_classes
        t = np.arange(int(self.SR * self.DUR)) / self.SR
        self.labels = np.repeat(np.arange(self.n_classes),
                                self.N_PER_CLASS).astype("int64")
        # per-class fundamental + harmonics + noise: classes separable
        self.waves = []
        for lab in self.labels:
            f0 = 120.0 + 35.0 * lab
            w = (np.sin(2 * np.pi * f0 * t)
                 + 0.4 * np.sin(2 * np.pi * 2 * f0 * t)
                 + 0.08 * rng.randn(t.size))
            self.waves.append((w / np.abs(w).max()).astype("float32"))
        self._feat = None
        if feat_type in ("mel", "melspectrogram"):
            self._feat = MelSpectrogram(sr=self.SR, **feat_kw)
        elif feat_type in ("logmel", "logmelspectrogram"):
            self._feat = LogMelSpectrogram(sr=self.SR, **feat_kw)
        elif feat_type == "spectrogram":
            self._feat = Spectrogram(**feat_kw)
        elif feat_type != "raw":
            raise ValueError(f"unknown feat_type {feat_type!r}")

    def __getitem__(self, idx):
        w = self.waves[idx]
        if self._feat is not None:
            from ...framework.core import Tensor
            import jax.numpy as jnp
            out = self._feat(Tensor(jnp.asarray(w)[None, :]))
            return np.asarray(out._value)[0], self.labels[idx]
        return w, self.labels[idx]

    def __len__(self):
        return len(self.waves)


class TESS(_SyntheticAudioDataset):
    """reference: paddle.audio.datasets.TESS — 7 emotion classes."""
    n_classes = 7
    label_list = ["angry", "disgust", "fear", "happy", "neutral",
                  "ps", "sad"]


class ESC50(_SyntheticAudioDataset):
    """reference: paddle.audio.datasets.ESC50 — 50 environmental
    sound classes."""
    n_classes = 50
    N_PER_CLASS = 2
    label_list = [f"class_{i}" for i in range(50)]
