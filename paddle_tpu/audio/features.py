"""paddle.audio.features (reference:
python/paddle/audio/features/layers.py — Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC as Layers)."""
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import call_op
from .. import nn
from ..signal import stft
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    """|STFT|^power over (N, T) or (T,) waveforms ->
    (N, n_fft//2+1, num_frames)."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = F.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        return call_op(
            lambda s: jnp.abs(s) ** self.power
            if self.power != 2.0 else (s.real * s.real + s.imag * s.imag),
            spec)


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype)
        self.fbank = F.compute_fbank_matrix(
            sr, n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk,
            norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self.spectrogram(x)          # (..., freq, frames)
        return call_op(lambda s, fb: jnp.einsum("mf,...ft->...mt", fb, s),
                       spec, self.fbank)


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db, dtype)
        self.dct = F.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        lm = self.logmel(x)                 # (..., n_mels, frames)
        return call_op(lambda s, d: jnp.einsum("mk,...mt->...kt", d, s),
                       lm, self.dct)
