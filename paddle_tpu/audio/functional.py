"""paddle.audio.functional (reference:
python/paddle/audio/functional/{window,functional}.py — mel scale
conversions, filterbank construction, dct, window functions)."""
import math

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    """Hz -> mel (slaney by default, matching the reference)."""
    scalar = np.isscalar(freq)
    f = np.asarray(freq, dtype=np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep,
                       out)
    return float(out) if scalar else out


def mel_to_hz(mel, htk=False):
    scalar = np.isscalar(mel)
    m = np.asarray(mel, dtype=np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return float(out) if scalar else out


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0.0, sr / 2.0, n_fft // 2 + 1)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank: (n_mels, n_fft//2 + 1)."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        fb = fb * enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=None):
    """10*log10(spect/ref) with floor and optional dynamic-range cap."""
    x = spect._value if isinstance(spect, Tensor) else jnp.asarray(spect)
    db = 10.0 * jnp.log10(jnp.maximum(x, amin))
    db = db - 10.0 * jnp.log10(jnp.maximum(jnp.asarray(ref_value), amin))
    if top_db is not None:
        db = jnp.maximum(db, jnp.max(db) - top_db)
    return Tensor(db)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix (n_mels, n_mfcc) — reference layout: logmel @ dct."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    return Tensor(jnp.asarray(dct.astype(dtype)))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window by name (reference: audio/functional/window.py)."""
    name = window if isinstance(window, str) else window[0]
    M = win_length + (1 if fftbins else 0)  # periodic vs symmetric
    if name in ("hann", "hanning"):
        w = np.hanning(M)
    elif name == "hamming":
        w = np.hamming(M)
    elif name == "blackman":
        w = np.blackman(M)
    elif name == "bartlett":
        w = np.bartlett(M)
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(M)
    elif name == "gaussian":
        std = window[1] if not isinstance(window, str) else 7.0
        n = np.arange(M) - (M - 1) / 2.0
        w = np.exp(-0.5 * (n / std) ** 2)
    else:
        raise ValueError(f"unsupported window {window!r}")
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w.astype(dtype)))
