"""User-facing autograd package (reference: python/paddle/autograd/ —
py_layer.py custom functions, backward entry, no_grad helpers).

TPU-native: ``PyLayer`` plugs a user-defined backward directly into the
eager tape as one custom ``Node`` whose vjp closure calls the user's
``backward`` — the exact analogue of the reference's ``PyLayerOp`` grad node
wired through ``egr::Backward``.  Inside jit/to_static traces (tape
suspended) the same class lowers to ``jax.custom_vjp`` semantics by running
the user backward on tracers.
"""
import weakref

import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import autograd as _ag
from ..framework.autograd import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad)

__all__ = ["PyLayer", "PyLayerContext", "backward", "no_grad", "enable_grad",
           "is_grad_enabled", "set_grad_enabled", "grad", "hessian",
           "jacobian", "saved_tensors_hooks"]

# active (pack, unpack) hook pairs (reference: paddle.autograd
# saved_tensors_hooks over the eager saved-tensor slots).  Scope note:
# the implicit residuals of jnp ops live inside jax.vjp closures (XLA
# manages them); the hookable surface — as in the reference for custom
# ops — is PyLayer's explicit save_for_backward/saved_tensor.
_SAVED_TENSOR_HOOKS = []


class saved_tensors_hooks:
    """Context manager: while active, PyLayer.save_for_backward routes
    every tensor through ``pack_hook`` and ``saved_tensor`` routes the
    packed value back through ``unpack_hook`` (e.g. offload-to-host /
    reload, or fp8 compression)."""

    def __init__(self, pack_hook, unpack_hook):
        self.pair = (pack_hook, unpack_hook)

    def __enter__(self):
        _SAVED_TENSOR_HOOKS.append(self.pair)
        return self

    def __exit__(self, *exc):
        _SAVED_TENSOR_HOOKS.remove(self.pair)
        return False


class PyLayerContext:
    """Context passed to PyLayer.forward/backward (reference:
    python/paddle/autograd/py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self._materialize_grads = True
        self._non_differentiable = set()

    def save_for_backward(self, *tensors):
        if _SAVED_TENSOR_HOOKS:
            pack, unpack = _SAVED_TENSOR_HOOKS[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._unpack_hook = unpack
        else:
            self._saved = tuple(tensors)
            self._unpack_hook = None

    def saved_tensor(self):
        if getattr(self, "_unpack_hook", None) is not None:
            return tuple(self._unpack_hook(t) for t in self._saved)
        return self._saved

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tuple(tensors)

    def mark_non_differentiable(self, *tensors):
        for t in tensors:
            if isinstance(t, Tensor):
                t.stop_gradient = True
                self._non_differentiable.add(id(t))

    def set_materialize_grads(self, value):
        self._materialize_grads = bool(value)


class _PyLayerNode(_ag.Node):
    """Tape node whose vjp is the user's ``backward(ctx, *grads)``."""
    __slots__ = ("ctx", "cls", "n_tensor_inputs")

    def __init__(self, cls, ctx, inputs, outputs, single_out):
        self.cls = cls
        self.ctx = ctx
        self.n_tensor_inputs = len(inputs)
        super().__init__(self._user_vjp, inputs, outputs, single_out)
        self.materialize_grads = ctx._materialize_grads

    def _call_user_backward(self, grads_in, taped):
        """Run cls.backward and normalize its result.

        ``taped=False``: tape off, returns raw jnp values (vjp path).
        ``taped=True`` (create_graph): tape stays ON so the user
        backward's computation is differentiable again, returns Tensors.
        """
        if self.cls is None:
            raise RuntimeError(
                "trying to backward through a graph that has already been "
                "freed; call backward(retain_graph=True) if you need to "
                "backward twice")
        if taped:
            out = self.cls.backward(self.ctx, *grads_in)
        else:
            with _ag.no_grad():
                out = self.cls.backward(self.ctx, *grads_in)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        if len(out) != self.n_tensor_inputs:
            raise ValueError(
                f"{self.cls.__name__}.backward returned {len(out)} gradients "
                f"but forward received {self.n_tensor_inputs} Tensor inputs")
        vals = []
        for g, t in zip(out, self.inputs):
            if g is None:
                z = jnp.zeros(t._value.shape, t._value.dtype)
                vals.append(Tensor(z, stop_gradient=True) if taped else z)
            elif taped:
                vals.append(g if isinstance(g, Tensor)
                            else Tensor(jnp.asarray(g), stop_gradient=True))
            else:
                vals.append(g._value if isinstance(g, Tensor)
                            else jnp.asarray(g))
        return tuple(vals)

    def _user_vjp(self, cots):
        cot_list = [cots] if self.single_out else list(cots)
        # with set_materialize_grads(False) unused outputs arrive as None
        grads_in = tuple(None if c is None else Tensor(c, stop_gradient=True)
                         for c in cot_list)
        return self._call_user_backward(grads_in, taped=False)

    def release(self):
        self.ctx = None
        self.cls = None
        super().release()

    def apply_vjp_taped(self, out_cots):
        """create_graph path: run the user's ``backward`` with the tape ON
        so its computation is differentiable again (the reference requires
        PyLayer.backward to be differentiable for double-grad too)."""
        return self._call_user_backward(tuple(out_cots), taped=True)


class PyLayer:
    """Custom differentiable function (reference:
    python/paddle/autograd/py_layer.py class PyLayer).

    Subclass with static ``forward(ctx, *args, **kwargs)`` and
    ``backward(ctx, *grad_outputs)``; invoke via ``apply``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        if _ag._TAPE_SUSPENDED[0]:
            # inside a jit/to_static trace: lower to jax.custom_vjp so the
            # user backward survives jax.grad of the traced function
            return cls._apply_traced(args, kwargs)
        ctx = PyLayerContext()
        if _ag._JOURNAL[0] is not None:
            # PyLayer records its own tape node, invisible to the op
            # journal — block-level SOT replay would drop it
            _ag._JOURNAL[0].unsupported = "PyLayer.apply in forward"
        tensor_inputs = tuple(
            a for a in list(args) + list(kwargs.values())
            if isinstance(a, Tensor))
        record = _ag.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        with _ag.suspend_tape():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        input_ids = {id(t) for t in tensor_inputs}
        # re-wrap outputs that alias an input (identity-returning forwards)
        # — attaching the node to the input itself would create a self-cycle
        # in the tape and backward would silently never run
        out_tensors = []
        for o in outs:
            if not isinstance(o, Tensor):
                o = Tensor(o)
            elif id(o) in input_ids:
                was_nd = id(o) in ctx._non_differentiable
                o = Tensor(o._value, stop_gradient=o.stop_gradient)
                if was_nd:
                    ctx._non_differentiable.add(id(o))
            out_tensors.append(o)
        if not record:
            return out_tensors[0] if single else tuple(out_tensors)
        # all outputs join the node (backward sees one cotangent per output);
        # only those not marked non-differentiable carry gradient
        node = _PyLayerNode(cls, ctx, tensor_inputs, out_tensors, single)
        for i, o in enumerate(out_tensors):
            if id(o) not in ctx._non_differentiable:
                o.stop_gradient = False
            o._node = node
            o._out_idx = i
        return out_tensors[0] if single else tuple(out_tensors)

    @classmethod
    def _apply_traced(cls, args, kwargs):
        """Trace-time lowering: one jax.custom_vjp per call site.

        The forward/backward run on raw jnp values wrapped in Tensors with
        the tape already suspended; non-tensor ctx attributes survive via a
        closure cell (fwd and bwd trace within the same apply call).
        """
        import jax
        slots, vals = [], []
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                slots.append(("a", i))
                vals.append(a._value)
        for k, a in kwargs.items():
            if isinstance(a, Tensor):
                slots.append(("k", k))
                vals.append(a._value)

        def run_forward(ctx, vs):
            new_args, new_kwargs = list(args), dict(kwargs)
            for (kind, key), v in zip(slots, vs):
                t = Tensor(v, stop_gradient=False)
                if kind == "a":
                    new_args[key] = t
                else:
                    new_kwargs[key] = t
            out = cls.forward(ctx, *new_args, **new_kwargs)
            single = not isinstance(out, (tuple, list))
            outs = [out] if single else list(out)
            return tuple(o._value if isinstance(o, Tensor) else jnp.asarray(o)
                         for o in outs), single

        meta = {}  # single-flag + live ctx, written at trace time

        @jax.custom_vjp
        def f(*vs):
            ctx = PyLayerContext()
            outs, single = run_forward(ctx, vs)
            meta["single"] = single
            return outs

        def f_fwd(*vs):
            ctx = PyLayerContext()
            outs, single = run_forward(ctx, vs)
            meta["single"] = single
            meta["ctx"] = ctx
            saved = tuple(t._value if isinstance(t, Tensor) else jnp.asarray(t)
                          for t in ctx._saved)
            return outs, saved

        in_avals = [(v.shape, v.dtype) for v in vals]

        def f_bwd(saved, cots):
            ctx = meta.get("ctx") or PyLayerContext()
            ctx._saved = tuple(Tensor(s, stop_gradient=True) for s in saved)
            grads_in = tuple(Tensor(c, stop_gradient=True) for c in cots)
            out = cls.backward(ctx, *grads_in)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            if len(out) != len(in_avals):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(out)} gradients "
                    f"but forward received {len(in_avals)} Tensor inputs")
            res = []
            for g, (shape, dtype) in zip(out, in_avals):
                if g is None:
                    res.append(jnp.zeros(shape, dtype))
                else:
                    res.append(g._value if isinstance(g, Tensor)
                               else jnp.asarray(g))
            return tuple(res)

        f.defvjp(f_fwd, f_bwd)
        out_vals = f(*vals)
        out_tensors = [Tensor(o, stop_gradient=True) for o in out_vals]
        return out_tensors[0] if meta["single"] else tuple(out_tensors)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward — seed multiple roots at once."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = (grad_tensors if isinstance(grad_tensors, (list, tuple))
                    else [grad_tensors])
    if len(grad_tensors) != len(tensors):
        raise ValueError(
            f"grad_tensors has {len(grad_tensors)} entries but tensors has "
            f"{len(tensors)}; they must match one-to-one")
    seeds = {}
    for t, g in zip(tensors, grad_tensors):
        gv = jnp.ones_like(t._value) if g is None else g._value
        if t._node is None:
            if not t.stop_gradient:
                _ag._accumulate(t, gv)
            continue
        key = (id(t._node), t._out_idx)
        if key in seeds:
            seeds[key] = (t._node, seeds[key][1] + gv)
        else:
            seeds[key] = (t._node, gv)
    if seeds:
        _ag._run_backward(seeds, retain_graph, sink_map=None)


def _functional_value_fn(func, n_inputs):
    """Lift a Tensor->Tensor framework function to a jnp value function
    (tape suspended so jax transforms can trace through it)."""
    def vf(*vals):
        with _ag.suspend_tape():
            ts = [Tensor(v, stop_gradient=True) for v in vals]
            out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out
    return vf


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """paddle.incubate.autograd.jacobian-shaped functional Jacobian.

    Returns a pytree mirroring (output structure) × (xs structure), with
    each leaf wrapped as a Tensor.
    """
    import jax
    single_x = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single_x else list(xs)
    vals = [x._value for x in xs_list]
    vf = _functional_value_fn(func, len(vals))
    argnums = 0 if single_x else tuple(range(len(vals)))
    jac = jax.jacrev(vf, argnums=argnums)(*vals)
    wrap = lambda leaf: Tensor(leaf, stop_gradient=not create_graph)
    return jax.tree_util.tree_map(wrap, jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Functional Hessian of a scalar-output func (pytree mirroring
    xs structure × xs structure)."""
    import jax
    single_x = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single_x else list(xs)
    vals = [x._value for x in xs_list]
    vf = _functional_value_fn(func, len(vals))
    argnums = 0 if single_x else tuple(range(len(vals)))
    hes = jax.hessian(vf, argnums=argnums)(*vals)
    wrap = lambda leaf: Tensor(leaf, stop_gradient=not create_graph)
    return jax.tree_util.tree_map(wrap, hes)


def jvp(func, xs, v=None):
    """Forward-mode Jacobian-vector product."""
    import jax
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = tuple(x._value for x in xs_list)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(t._value for t in v_list)
    vf = _functional_value_fn(func, len(vals))
    out, tangent_out = jax.jvp(vf, vals, tangents)
    wrap = lambda o: Tensor(o, stop_gradient=True)
    if isinstance(out, tuple):
        return tuple(map(wrap, out)), tuple(map(wrap, tangent_out))
    return wrap(out), wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode vector-Jacobian product."""
    import jax
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = tuple(x._value for x in xs_list)
    vf = _functional_value_fn(func, len(vals))
    out, pullback = jax.vjp(vf, *vals)
    if v is None:
        seed = (tuple(jnp.ones_like(o) for o in out)
                if isinstance(out, tuple) else jnp.ones_like(out))
    elif isinstance(v, (list, tuple)):
        seed = tuple(t._value for t in v)
    else:
        seed = v._value
    grads = pullback(seed)
    wrap = lambda o: Tensor(o, stop_gradient=True)
    out_w = (tuple(map(wrap, out)) if isinstance(out, tuple) else wrap(out))
    grads_w = tuple(map(wrap, grads))
    if not isinstance(xs, (list, tuple)):
        return out_w, grads_w[0]
    return out_w, grads_w
