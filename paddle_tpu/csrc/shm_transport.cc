// POSIX shared-memory batch transport for the multiprocess DataLoader
// (reference: python/paddle/io/dataloader's use_shared_memory=True path —
// _share_memory tensors + paddle/fluid/memory/allocation shared-memory
// segments).  Worker processes serialize a batch's arrays into one shm
// segment and pass only (name, layout) through the result queue; the
// consumer maps the segment, builds zero-copy views, and unlinks.  This
// removes the pickle+pipe double copy for large batches.
//
// API (ctypes, see framework/native.py):
//   pt_shm_create(name, bytes)  -> handle  (worker: create+map, O_EXCL)
//   pt_shm_attach(name)         -> handle  (consumer: map existing)
//   pt_shm_ptr(handle)          -> uint8_t* (base address)
//   pt_shm_size(handle)         -> int64   (segment bytes)
//   pt_shm_write(handle, off, src, len) / pt_shm_read(handle, off, dst, len)
//   pt_shm_close(handle, unlink) (munmap+close; unlink!=0 removes the name)
//   pt_shm_unlink(name)         (cleanup of a segment by name alone)

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

#include "common.h"

namespace {

struct ShmSeg {
  void* addr = nullptr;
  int64_t size = 0;
  std::string name;
};

}  // namespace

PT_EXPORT int64_t pt_shm_create(const char* name, int64_t bytes) {
  if (bytes <= 0) return 0;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return 0;
  if (ftruncate(fd, bytes) != 0) {
    close(fd);
    shm_unlink(name);
    return 0;
  }
  void* addr = mmap(nullptr, static_cast<size_t>(bytes),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);  // mapping keeps the segment alive
  if (addr == MAP_FAILED) {
    shm_unlink(name);
    return 0;
  }
  auto* seg = new ShmSeg{addr, bytes, name};
  return reinterpret_cast<int64_t>(seg);
}

PT_EXPORT int64_t pt_shm_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return 0;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return 0;
  }
  void* addr = mmap(nullptr, static_cast<size_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (addr == MAP_FAILED) return 0;
  auto* seg = new ShmSeg{addr, static_cast<int64_t>(st.st_size), name};
  return reinterpret_cast<int64_t>(seg);
}

PT_EXPORT uint8_t* pt_shm_ptr(int64_t h) {
  auto* seg = reinterpret_cast<ShmSeg*>(h);
  return seg ? reinterpret_cast<uint8_t*>(seg->addr) : nullptr;
}

PT_EXPORT int64_t pt_shm_size(int64_t h) {
  auto* seg = reinterpret_cast<ShmSeg*>(h);
  return seg ? seg->size : 0;
}

PT_EXPORT int pt_shm_write(int64_t h, int64_t off, const uint8_t* src,
                           int64_t len) {
  auto* seg = reinterpret_cast<ShmSeg*>(h);
  if (!seg || off < 0 || len < 0 || off + len > seg->size) return -1;
  std::memcpy(reinterpret_cast<uint8_t*>(seg->addr) + off, src,
              static_cast<size_t>(len));
  return 0;
}

PT_EXPORT int pt_shm_read(int64_t h, int64_t off, uint8_t* dst, int64_t len) {
  auto* seg = reinterpret_cast<ShmSeg*>(h);
  if (!seg || off < 0 || len < 0 || off + len > seg->size) return -1;
  std::memcpy(dst, reinterpret_cast<uint8_t*>(seg->addr) + off,
              static_cast<size_t>(len));
  return 0;
}

PT_EXPORT void pt_shm_close(int64_t h, int unlink_it) {
  auto* seg = reinterpret_cast<ShmSeg*>(h);
  if (!seg) return;
  munmap(seg->addr, static_cast<size_t>(seg->size));
  if (unlink_it) shm_unlink(seg->name.c_str());
  delete seg;
}

PT_EXPORT void pt_shm_unlink(const char* name) { shm_unlink(name); }
