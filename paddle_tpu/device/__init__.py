"""Device API (reference: python/paddle/device/)."""
import jax

from ..framework.core import set_device, get_device  # noqa: F401

__all__ = ["set_device", "get_device", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cuda", "cuda"]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_cuda():
    return False


def device_count():
    return len(jax.devices())


class _CudaNamespace:
    """paddle.device.cuda compat — mapped onto the TPU device."""

    @staticmethod
    def device_count():
        return len([d for d in jax.devices() if d.platform != "cpu"])

    @staticmethod
    def memory_allocated(device=None):
        try:
            dev = jax.devices()[0]
            stats = dev.memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            dev = jax.devices()[0]
            stats = dev.memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_reserved(device=None):
        return _CudaNamespace.memory_allocated(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return _CudaNamespace.max_memory_allocated(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def synchronize(device=None):
        for d in jax.live_arrays():
            d.block_until_ready()
            break

    @staticmethod
    def current_stream(device=None):
        """PJRT owns streams; the module-level singleton keeps identity
        checks working across calls."""
        return current_stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)   # module-level guard (sets current)

    @staticmethod
    def get_device_properties(device=None):
        import collections
        dev = jax.devices()[0]
        total = 0
        try:
            total = dev.memory_stats().get("bytes_limit", 0)
        except Exception:
            pass
        Props = collections.namedtuple(
            "_gpuDeviceProperties",
            ["name", "major", "minor", "total_memory", "multi_processor_count"])
        return Props(dev.device_kind, 0, 0, total, 1)

    @staticmethod
    def get_device_name(device=None):
        return jax.devices()[0].device_kind

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)

    class Event:
        def __init__(self, *a, **k):
            pass

        def record(self, *a):
            pass

        def synchronize(self):
            pass

    class Stream:
        def __init__(self, *a, **k):
            pass

        def synchronize(self):
            pass


cuda = _CudaNamespace()


def synchronize(device=None):
    cuda.synchronize()


class tpu:
    """paddle.device.tpu — first-class device namespace."""
    device_count = staticmethod(_CudaNamespace.device_count)
    memory_allocated = staticmethod(_CudaNamespace.memory_allocated)
    max_memory_allocated = staticmethod(_CudaNamespace.max_memory_allocated)
    synchronize = staticmethod(_CudaNamespace.synchronize)


def get_all_device_type():
    """reference: paddle.device.get_all_device_type."""
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def is_compiled_with_xpu():
    return False


def get_cudnn_version():
    return None


# -- stream/event surface (reference: paddle.device.Stream/Event) -----------
# PJRT/XLA own scheduling on TPU: one compiled program per device, no
# user-visible streams.  The API class exists for parity; synchronize is
# the only operation with real semantics (device barrier).

class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_CURRENT_STREAM = Stream()


def current_stream(device=None):
    return _CURRENT_STREAM


def set_stream(stream):
    global _CURRENT_STREAM
    prev = _CURRENT_STREAM
    _CURRENT_STREAM = stream
    return prev


from contextlib import contextmanager as _ctx


@_ctx
def stream_guard(stream):
    prev = set_stream(stream)
    try:
        yield
    finally:
        set_stream(prev)
