"""Distributed API (reference: python/paddle/distributed/).

M2 fills this out (mesh topology, comm API over shard_map, DataParallel,
sharding); this module provides the env/bootstrap layer used everywhere.
"""
import os

from . import env as _env
from .env import (get_rank, get_world_size, init_parallel_env,  # noqa: F401
                  ParallelEnv, is_initialized, is_available,
                  parallel_device_count)
from .collective import (all_reduce, all_gather, all_gather_object,  # noqa: F401
                         reduce_scatter, alltoall, alltoall_single,
                         broadcast, reduce, scatter, send, recv, barrier,
                         new_group, wait, get_group, destroy_process_group,
                         ReduceOp, stream, broadcast_object_list,
                         scatter_object_list, gather, isend, irecv,
                         P2POp, batch_isend_irecv, get_backend)
from .parallel import DataParallel, split  # noqa: F401
from .mesh import (ProcessMesh, get_mesh, set_mesh, auto_mesh,  # noqa: F401
                   shard_tensor, shard_op, Shard, Replicate, Partial,
                   reshard, dtensor_from_fn, shard_layer)
from .checkpoint import (save_state_dict,  # noqa: F401
                         load_state_dict)
from .store import TCPStore, MasterStore  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import rpc  # noqa: F401
from . import checkpoint  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import utils  # noqa: F401
from . import communication  # noqa: F401
from . import passes  # noqa: F401
from .spawn import spawn  # noqa: F401


def launch():
    from .launch.main import main
    main()
