"""Semi-auto parallel API (reference:
python/paddle/distributed/auto_parallel/ — Engine
(auto_parallel/static/engine.py: fit/evaluate/predict over the
auto-completed distributed program), Strategy, shard_tensor annotations).

TPU-native: the annotation layer (ProcessMesh / shard_tensor / shard_op /
reshard, distributed/mesh.py) marks placements and GSPMD does the
completion/partition/reshard passes that the reference implements in
Python+C++ (SURVEY §7.1).  Engine is therefore a thin driver: it builds
a PlacementPlan from the Strategy (or an auto data-parallel plan), pins
it on the model, and delegates the epoch loop to the hapi Model stepper,
which compiles one SPMD train step from the plan.
"""
import jax

from ..mesh import (ProcessMesh, shard_tensor, shard_op, reshard,  # noqa: F401
                    Shard, Replicate, Partial, get_mesh, set_mesh)
from ..engine import PlacementPlan, make_data_parallel_plan, plan_from_hcg

__all__ = ["Engine", "Strategy", "ProcessMesh", "shard_tensor", "shard_op",
           "reshard", "Shard", "Replicate", "Partial"]


class Strategy:
    """auto_parallel.Strategy (reference: auto_parallel/strategy.py) —
    dataclass-style knobs; the meaningful-on-TPU subset."""

    class _Section(dict):
        def __getattr__(self, k):
            return self.get(k)

        def __setattr__(self, k, v):
            self[k] = v

    def __init__(self, config=None):
        self.amp = self._Section(enable=False, dtype="bfloat16", level="O1")
        self.sharding = self._Section(enable=False, stage=1, degree=1)
        self.recompute = self._Section(enable=False)
        self.pipeline = self._Section(enable=False, schedule_mode="1F1B",
                                      accumulate_steps=1,
                                      micro_batch_size=None)
        self.mp_degree = 1
        self.dp_degree = 1
        self.pp_degree = 1
        self.sep_degree = 1     # ring/Ulysses sequence parallelism
        self.ep_degree = 1      # expert parallelism (MoE)
        if config:
            for k, v in config.items():
                setattr(self, k, v)


class Engine:
    """auto_parallel.Engine parity: fit/evaluate/predict on a model whose
    tensors may carry ProcessMesh placements.  The heavy lifting
    (partitioning, resharding, collective insertion) is GSPMD's; Engine
    assembles the plan + compiled stepper."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._network = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics
        self._strategy = strategy or Strategy()
        self._model = None

    # -- plan ----------------------------------------------------------------
    def _degrees(self):
        """Resolve (dp, sharding, mp, pp, sep, ep) from the Strategy +
        world size.  Explicit degrees win; dp absorbs the remainder."""
        s = self._strategy
        n = jax.device_count()
        mp = int(getattr(s, "mp_degree", 1) or 1)
        pp = int(getattr(s, "pp_degree", 1) or 1) \
            if s.pipeline.get("enable") else 1
        sep = int(getattr(s, "sep_degree", 1) or 1)
        ep = int(getattr(s, "ep_degree", 1) or 1)
        sh = 1
        if s.sharding.get("enable"):
            sh = int(s.sharding.get("degree", 1) or 1)
            if sh <= 1:
                # degree unset: shard across everything left over
                sh = max(n // (mp * pp * sep * ep), 1)
        dp_explicit = int(getattr(s, "dp_degree", 0) or 0)
        # the default dp_degree=1 means "infer": dp absorbs the devices
        # left over after mp/pp/sharding/sep/ep; an explicit >1 value wins
        dp = dp_explicit if dp_explicit > 1 \
            else max(n // (mp * pp * sh * sep * ep), 1)
        return dp, sh, mp, pp, sep, ep

    def _build_plan(self):
        """dp x sharding x sep x expert x model mesh honoring the Strategy
        degrees (the pp axis is handled by the fleet _PipelineStepper
        route, not here).  The axis names match the fleet topology plus
        the dedicated "expert" axis, so sep_attention's auto-shard_map
        and MoE's expert-pspec land on the right devices."""
        s = self._strategy
        level = None
        if s.sharding.get("enable"):
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(
                s.sharding.get("stage", 1), "os")
        dp, sh, mp, _, sep, ep = self._degrees()
        # (re)register the ambient sep mesh for THIS plan — and clear a
        # stale one from a previous Engine when this plan has no sep
        # axis, so sep_attention outside shard_map fails loudly instead
        # of silently riding an old topology
        from ..fleet.utils.sep_utils import set_sep_mesh
        if sh > 1 or mp > 1 or sep > 1 or ep > 1:
            import numpy as np
            from jax.sharding import Mesh
            n = dp * sh * sep * ep * mp
            mesh = Mesh(
                np.asarray(jax.devices()[:n]).reshape(dp, sh, sep, ep, mp),
                ("data", "sharding", "sep", "expert", "model"))
            set_sep_mesh(mesh if sep > 1 else None)
            return PlacementPlan(mesh, level=level)
        set_sep_mesh(None)
        return make_data_parallel_plan(level=level)

    def _rebind_expert_axis(self, net):
        """Strategy.ep_degree > 1: route MoE layers onto the dedicated
        "expert" mesh axis (their default is "model", which the TP axis
        owns) by rewriting the stacked-expert param pspecs."""
        if self._degrees()[5] <= 1:
            return
        from ...incubate.distributed.models.moe import MoELayer
        for sub in net.sublayers(include_self=True):
            if isinstance(sub, MoELayer) and sub.expert_axis != "expert":
                sub.expert_axis = "expert"
                for nm in ("expert_w1", "expert_b1",
                           "expert_w2", "expert_b2"):
                    p = getattr(sub, nm, None)
                    if p is not None and getattr(p, "pspec", None):
                        p.pspec = ("expert",) + tuple(p.pspec[1:])

    def _is_pipeline(self):
        from ..fleet.meta_parallel import PipelineLayer
        return bool(self._strategy.pipeline.get("enable")) and \
            isinstance(self._network, PipelineLayer)

    def _ensure_model(self):
        if self._model is not None:
            return self._model
        if self._is_pipeline():
            self._model = self._build_pipeline_model()
            return self._model
        from ...hapi.model import Model
        net = self._network
        if getattr(net, "_placement_plan", None) is None:
            net._placement_plan = self._build_plan()
        self._rebind_expert_axis(net)
        m = Model(net)
        amp_level = None
        if self._strategy.amp.get("enable"):
            amp_level = self._strategy.amp.get("level", "O1")
        m.prepare(self._optimizer, self._loss, self._metrics,
                  amp_configs=amp_level)
        self._model = m
        return m

    def _build_pipeline_model(self):
        """Route Strategy.pipeline through the fleet SPMD pipeline
        stepper (reference: auto_parallel/static/engine.py drives pp
        through the same parallelizer the fleet API uses)."""
        from .. import fleet
        s = self._strategy
        dp, sh, mp, pp, sep, ep = self._degrees()
        if ep > 1:
            raise NotImplementedError(
                "Engine: ep_degree > 1 with Strategy.pipeline is not "
                "supported (the fleet topology has no expert axis); use "
                "the non-pipeline Engine path for MoE models")
        fs = fleet.DistributedStrategy()
        fs.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                             "pp_degree": pp, "sharding_degree": sh,
                             "sep_degree": sep}
        pcfg = {"accumulate_steps":
                int(s.pipeline.get("accumulate_steps", 1) or 1)}
        if s.pipeline.get("micro_batch_size"):
            pcfg["micro_batch_size"] = int(s.pipeline["micro_batch_size"])
        fs.pipeline_configs = pcfg
        if s.sharding.get("enable"):
            fs.sharding = True
            fs.sharding_configs = {"stage": s.sharding.get("stage", 1)}
        fleet.init(is_collective=True, strategy=fs)
        return fleet.distributed_model(self._network)

    @property
    def main_program(self):
        return None  # jaxpr/HLO is the program; kept for API parity

    def tune(self, batch_size, seq_len=None, n_devices=None,
             hbm_gb=16.0, stage=2, verbose=False):
        """Auto-sharding tuner v1 (VERDICT r4 #7): choose
        (dp, sharding, mp, pp) from the memory + collective-volume cost
        model in ``tuner.py`` and write the winning degrees into this
        Engine's Strategy.  Returns the chosen candidate dict.

        Reference: the auto-parallel cost model + tuner
        (auto_parallel/static/cost/, tuner/) that search the placement
        space; here the space is the mesh factorization because GSPMD
        owns per-op partitioning.
        """
        import jax as _jax
        from .tuner import ModelStats, tune as _tune
        n = n_devices or _jax.device_count()
        net = self._network
        cfg = getattr(net, "config", None)
        if cfg is not None and hasattr(cfg, "hidden_size"):
            stats = ModelStats.from_config(cfg, batch_size, seq_len)
        else:
            stats = ModelStats.from_layer(net, batch_size,
                                          seq_len or 1024)
        # pp candidates only for models the pipeline stepper can split
        allow_pp = self._is_pipeline()
        best, report = _tune(stats, n, allow_pp=allow_pp, stage=stage,
                             hbm_gb=hbm_gb)
        if verbose:
            for c in report[:8]:
                print(f"[tune] dp={c['dp']} sh={c['sharding']} "
                      f"mp={c['mp']} pp={c['pp']} mem={c['mem_gb']}GB "
                      f"cost={c['cost_s']*1e3:.2f}ms "
                      f"feasible={c['feasible']}")
        # write the WHOLE winning placement — including disabling axes a
        # previous Strategy had on that the winner dropped, or _degrees()
        # would over-subscribe the mesh
        s = self._strategy
        s.dp_degree = best["dp"]
        s.mp_degree = best["mp"]
        s.sharding.enable = best["sharding"] > 1
        s.sharding.degree = best["sharding"]
        if best["sharding"] > 1:
            s.sharding.stage = best["stage"]
        s.pipeline.enable = best["pp"] > 1
        s.pp_degree = best["pp"]
        self._model = None        # force plan rebuild with new degrees
        if getattr(self._network, "_placement_plan", None) is not None:
            # a prior fit() pinned a plan on the net; the tuned degrees
            # must not be silently ignored
            self._network._placement_plan = None
        return best

    # -- user surface --------------------------------------------------------
    def _batches(self, data, batch_size, collate_fn, shuffle,
                 drop_last=False):
        from ...io import DataLoader, Dataset
        if isinstance(data, (list, tuple)):
            return data    # pre-made batches
        if isinstance(data, Dataset) or (hasattr(data, "__getitem__")
                                         and hasattr(data, "__len__")):
            # drop_last only on the train path (micro-batch divisibility);
            # evaluate/predict must see every sample
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              collate_fn=collate_fn, drop_last=drop_last)
        return data    # already an iterable of batches

    def fit(self, train_data, valid_data=None, train_sample_split=None,
            batch_size=1, epochs=1, steps_per_epoch=None, log_freq=10,
            save_dir=None, save_freq=1, valid_freq=1, valid_steps=None,
            collate_fn=None, callbacks=None, verbose=2, nvprof_range=None):
        if self._is_pipeline():
            m = self._ensure_model()
            hist = {"loss": []}
            for ep in range(epochs):
                for it, batch in enumerate(
                        self._batches(train_data, batch_size, collate_fn,
                                      shuffle=True, drop_last=True)):
                    if steps_per_epoch and it >= steps_per_epoch:
                        break
                    data = [b.numpy() if hasattr(b, "numpy") else b
                            for b in batch]
                    loss = m.train_batch(data, self._optimizer)
                    hist["loss"].append(float(loss))
                    if verbose and log_freq and it % log_freq == 0:
                        print(f"[Engine/pp] epoch {ep} step {it} "
                              f"loss {float(loss):.4f}")
            return hist
        m = self._ensure_model()
        return m.fit(train_data, eval_data=valid_data,
                     batch_size=batch_size, epochs=epochs,
                     eval_freq=valid_freq, log_freq=log_freq,
                     save_dir=save_dir, save_freq=save_freq,
                     verbose=verbose, callbacks=callbacks)

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=2):
        if self._is_pipeline():
            m = self._ensure_model()
            losses = []
            for it, batch in enumerate(
                    self._batches(valid_data, batch_size, collate_fn,
                                  shuffle=False)):
                if steps and it >= steps:
                    break
                data = [b.numpy() if hasattr(b, "numpy") else b
                        for b in batch]
                losses.append(float(m.eval_batch(data)))
            return {"loss": sum(losses) / max(len(losses), 1)}
        m = self._ensure_model()
        return m.evaluate(valid_data, batch_size=batch_size,
                          log_freq=log_freq, verbose=verbose,
                          callbacks=callbacks)

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2):
        if self._is_pipeline():
            m = self._ensure_model()
            outs = []
            for it, batch in enumerate(
                    self._batches(test_data, batch_size, collate_fn,
                                  shuffle=False)):
                if steps and it >= steps:
                    break
                data = [b.numpy() if hasattr(b, "numpy") else b
                        for b in batch]
                outs.append(m.eval_batch(data, compute_loss=False))
            return outs
        m = self._ensure_model()
        return m.predict(test_data, batch_size=batch_size, verbose=verbose,
                         callbacks=callbacks)

    def save(self, path, training=True):
        if self._is_pipeline():
            from ... import save as _save
            # the wrapper's state_dict syncs the trained stacked values
            # back into the block params; fall back to the raw layer if
            # fit was never called
            src = self._model if self._model is not None else self._network
            return _save(src.state_dict(), path + ".pdparams")
        return self._ensure_model().save(path, training=training)

    def load(self, path, strict=True, load_optimizer=True):
        if self._is_pipeline():
            from ... import load as _load
            self._network.set_state_dict(_load(path + ".pdparams"))
            return
        return self._ensure_model().load(
            path, reset_optimizer=not load_optimizer)
