"""Semi-auto parallel API (reference:
python/paddle/distributed/auto_parallel/ — Engine
(auto_parallel/static/engine.py: fit/evaluate/predict over the
auto-completed distributed program), Strategy, shard_tensor annotations).

TPU-native: the annotation layer (ProcessMesh / shard_tensor / shard_op /
reshard, distributed/mesh.py) marks placements and GSPMD does the
completion/partition/reshard passes that the reference implements in
Python+C++ (SURVEY §7.1).  Engine is therefore a thin driver: it builds
a PlacementPlan from the Strategy (or an auto data-parallel plan), pins
it on the model, and delegates the epoch loop to the hapi Model stepper,
which compiles one SPMD train step from the plan.
"""
import jax

from ..mesh import (ProcessMesh, shard_tensor, shard_op, reshard,  # noqa: F401
                    Shard, Replicate, Partial, get_mesh, set_mesh)
from ..engine import PlacementPlan, make_data_parallel_plan, plan_from_hcg

__all__ = ["Engine", "Strategy", "ProcessMesh", "shard_tensor", "shard_op",
           "reshard", "Shard", "Replicate", "Partial"]


class Strategy:
    """auto_parallel.Strategy (reference: auto_parallel/strategy.py) —
    dataclass-style knobs; the meaningful-on-TPU subset."""

    class _Section(dict):
        def __getattr__(self, k):
            return self.get(k)

        def __setattr__(self, k, v):
            self[k] = v

    def __init__(self, config=None):
        self.amp = self._Section(enable=False, dtype="bfloat16", level="O1")
        self.sharding = self._Section(enable=False, stage=1, degree=1)
        self.recompute = self._Section(enable=False)
        self.pipeline = self._Section(enable=False, schedule_mode="1F1B",
                                      accumulate_steps=1)
        self.mp_degree = 1
        self.dp_degree = 1
        if config:
            for k, v in config.items():
                setattr(self, k, v)


class Engine:
    """auto_parallel.Engine parity: fit/evaluate/predict on a model whose
    tensors may carry ProcessMesh placements.  The heavy lifting
    (partitioning, resharding, collective insertion) is GSPMD's; Engine
    assembles the plan + compiled stepper."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._network = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics
        self._strategy = strategy or Strategy()
        self._model = None

    # -- plan ----------------------------------------------------------------
    def _build_plan(self):
        s = self._strategy
        level = None
        if s.sharding.get("enable"):
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(
                s.sharding.get("stage", 1), "os")
        mp = getattr(s, "mp_degree", 1) or 1
        if mp > 1:
            import numpy as np
            from jax.sharding import Mesh
            n = jax.device_count()
            dp = max(n // mp, 1)
            mesh = Mesh(np.asarray(jax.devices()[:dp * mp]).reshape(dp, mp),
                        ("data", "model"))
            return PlacementPlan(mesh, level=level)
        return make_data_parallel_plan(level=level)

    def _ensure_model(self):
        if self._model is not None:
            return self._model
        from ...hapi.model import Model
        net = self._network
        if getattr(net, "_placement_plan", None) is None:
            net._placement_plan = self._build_plan()
        m = Model(net)
        amp_level = None
        if self._strategy.amp.get("enable"):
            amp_level = self._strategy.amp.get("level", "O1")
        m.prepare(self._optimizer, self._loss, self._metrics,
                  amp_configs=amp_level)
        self._model = m
        return m

    @property
    def main_program(self):
        return None  # jaxpr/HLO is the program; kept for API parity

    # -- user surface --------------------------------------------------------
    def fit(self, train_data, valid_data=None, train_sample_split=None,
            batch_size=1, epochs=1, steps_per_epoch=None, log_freq=10,
            save_dir=None, save_freq=1, valid_freq=1, valid_steps=None,
            collate_fn=None, callbacks=None, verbose=2, nvprof_range=None):
        m = self._ensure_model()
        return m.fit(train_data, eval_data=valid_data,
                     batch_size=batch_size, epochs=epochs,
                     eval_freq=valid_freq, log_freq=log_freq,
                     save_dir=save_dir, save_freq=save_freq,
                     verbose=verbose, callbacks=callbacks)

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=2):
        m = self._ensure_model()
        return m.evaluate(valid_data, batch_size=batch_size,
                          log_freq=log_freq, verbose=verbose,
                          callbacks=callbacks)

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2):
        m = self._ensure_model()
        return m.predict(test_data, batch_size=batch_size, verbose=verbose,
                         callbacks=callbacks)

    def save(self, path, training=True):
        return self._ensure_model().save(path, training=training)

    def load(self, path, strict=True, load_optimizer=True):
        return self._ensure_model().load(
            path, reset_optimizer=not load_optimizer)
