"""Auto-sharding tuner v1 (VERDICT r4 #7).

Reference: the auto-parallel cost model + tuner that search the
placement space (python/paddle/distributed/auto_parallel/static/cost/
and tuner/, SURVEY §2.2 auto-parallel row).  The reference costs
per-op distributed programs; here GSPMD owns partitioning, so the
search space is just the mesh factorization (dp, sharding, mp, pp) and
v1 costs each candidate with closed-form memory + communication models
of a transformer-shaped workload.

Per-device MEMORY (bytes), for P params, L layers, hidden H, batch B,
seq S, vocab V, Adam-style optimizer.  The sharding axis is DATA
parallel (ZeRO shards states over replicas), so activations divide by
dp*sh.  Activations assume per-layer remat (the framework's recompute
is standard at the scales where the tuner matters): stored = layer
inputs (2H bytes/token/layer) + one layer's working set:
  params     2P / (mp*pp) / (sh if stage==3 else 1)       (bf16 compute)
  grads      4P / (mp*pp) / (sh if stage>=2 else 1)       (fp32)
  optimizer 12P / (mp*pp) / (sh if stage>=1 else 1)       (fp32 m/v/master)
  acts       tok*(2H*(L/pp) + A_WORK*H),  tok = B*S/(dp*sh)
  logits     2*tok*V/mp * LOGITS_LIVE  (fwd act + bwd dlogits; under pp
             only the last stage holds it, for 1/n_micro of the batch)

Per-step COMMUNICATION time (bytes / ICI_BW), ring-collective factors:
  dp grad sync       2 * 4P/(mp*pp*max(sh,1)) * (dp-1)/dp
  sharding s>=2      same reduce-scatter+allgather volume as dp (folded
                     into the dp term via the flat data axis)
  sharding s==3      + 2 * 2P/(mp*pp) * (sh-1)/sh   (param allgather f+b)
  mp                 (L/pp) * 4 * 2 * 2*(B/dp)*S*H * (mp-1)/mp
  pp                 2 * 2*(B/dp)*S*H   (boundary sends, all micros)
COMPUTE time: 6*P*B*S tokens-flops / (n_devices * PEAK * EFF), with the
pipeline bubble multiplier (1 + (pp-1)/n_micro).

cost = compute*bubble + comm (no-overlap, conservative).  Feasible =
memory <= budget.  Among feasible candidates the lowest cost wins; ties
break toward plain dp (fewer axes, simpler program).
"""
from dataclasses import dataclass, field

__all__ = ["ModelStats", "estimate", "tune"]

# v5e-class constants — tunable via estimate()/tune() kwargs
ICI_BW = 90e9          # bytes/s per device, ring all-reduce effective
PEAK = 197e12          # bf16 flops
EFF = 0.45             # sustained fraction of peak for a train step
A_WORK = 30.0          # one layer's live working set, bytes/token/H
LOGITS_LIVE = 2.0      # fwd logits + bwd dlogits live together


@dataclass
class ModelStats:
    n_params: int
    n_layers: int
    hidden: int
    n_heads: int
    vocab: int
    batch: int
    seq: int

    @classmethod
    def from_config(cls, cfg, batch, seq=None):
        """From a GPTConfig-shaped object (hidden_size,
        num_hidden_layers, num_attention_heads, vocab_size)."""
        H = cfg.hidden_size
        L = cfg.num_hidden_layers
        V = cfg.vocab_size
        S = seq or getattr(cfg, "max_position_embeddings", 1024)
        n_params = V * H + S * H + L * 12 * H * H + 2 * H
        return cls(n_params=n_params, n_layers=L, hidden=H,
                   n_heads=cfg.num_attention_heads, vocab=V,
                   batch=batch, seq=S)

    @classmethod
    def from_layer(cls, net, batch, seq):
        """Heuristic extraction from a Layer: exact param count; layer
        count from repeated block types; hidden/vocab from the largest
        embedding-shaped parameter."""
        import numpy as np
        params = [p for _, p in net.named_parameters()]
        n_params = int(sum(int(np.prod(p.shape)) for p in params))
        from collections import Counter
        kinds = Counter(type(s).__name__ for s in net.sublayers())
        # the most-repeated composite block is "the layer"
        L = max([c for n, c in kinds.items()
                 if c > 1 and ("Layer" in n or "Block" in n
                               or "Decoder" in n or "Encoder" in n)],
                default=1)
        two_d = [tuple(p.shape) for p in params if len(p.shape) == 2]
        vocab, hidden = max(two_d, key=lambda s: s[0] * s[1],
                            default=(1, 1))
        if vocab < hidden:
            vocab, hidden = hidden, vocab
        heads = max(hidden // 64, 1)
        return cls(n_params=n_params, n_layers=L, hidden=hidden,
                   n_heads=heads, vocab=vocab, batch=batch, seq=seq)


def estimate(st, dp, sh, mp, pp, *, stage=2, n_micro=None,
             hbm_bytes=16e9, ici_bw=ICI_BW, peak=PEAK, eff=EFF):
    """Cost one (dp, sharding, mp, pp) candidate; returns a dict with
    mem_bytes, comm_s, compute_s, cost_s, feasible."""
    P, L, H, V = st.n_params, st.n_layers, st.hidden, st.vocab
    B, S = st.batch, st.seq
    n = dp * sh * mp * pp
    n_micro = n_micro or max(pp, 1)

    p_b = 2.0 * P / (mp * pp) / (sh if stage == 3 else 1)
    g_b = 4.0 * P / (mp * pp) / (sh if stage >= 2 else 1)
    o_b = 12.0 * P / (mp * pp) / (sh if stage >= 1 else 1)
    tok = B * S / (dp * sh)
    # remat assumed: layer inputs + one working set; 1F1B keeps pp
    # microbatch boundary inputs in flight per stage
    micro_tok = tok / (n_micro if pp > 1 else 1)
    act = micro_tok * (2.0 * H * (L / pp) * (pp if pp > 1 else 1)
                       + A_WORK * H / mp)
    logits = 2.0 * micro_tok * V / mp * LOGITS_LIVE
    mem = p_b + g_b + o_b + act + logits

    flat_data = dp * sh           # dp and sharding share the grad axis
    comm = 0.0
    if flat_data > 1:
        comm += 2.0 * (4.0 * P / (mp * pp)) / flat_data \
            * (flat_data - 1)
    if stage == 3 and sh > 1:
        comm += 2.0 * (2.0 * P / (mp * pp)) * (sh - 1) / sh
    # activation traffic scales with this device's tokens: the batch
    # splits across BOTH data axes (dp and ZeRO sharding)
    if mp > 1:
        comm += (L / pp) * 4 * 2 * (2.0 * tok * H) * (mp - 1) / mp
    if pp > 1:
        comm += 2 * (2.0 * tok * H)
    comm_s = comm / ici_bw

    compute_s = 6.0 * P * B * S / (n * peak * eff)
    bubble = 1.0 + (pp - 1) / max(n_micro, 1)
    cost = compute_s * bubble + comm_s
    return {"dp": dp, "sharding": sh, "mp": mp, "pp": pp,
            "stage": stage if sh > 1 else 0,
            "mem_bytes": mem, "mem_gb": round(mem / 1e9, 2),
            "comm_s": comm_s, "compute_s": compute_s,
            "bubble": bubble, "cost_s": cost,
            "feasible": mem <= hbm_bytes * 0.92}


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def tune(st, n_devices, *, allow_mp=True, allow_pp=True,
         allow_sharding=True, stage=2, hbm_gb=16.0, n_micro=None,
         ici_bw=ICI_BW, peak=PEAK, eff=EFF):
    """Search mesh factorizations of ``n_devices``; returns
    (best, report) where report lists every evaluated candidate sorted
    by cost (infeasible ones at the end).

    Constraints: mp must divide the head count, pp must divide the
    layer count, dp must divide the batch.  If nothing is feasible the
    lowest-memory candidate is returned with feasible=False so the
    caller can see how far over budget the model is.
    """
    hbm = hbm_gb * 1e9
    report = []
    for mp in (_divisors(n_devices) if allow_mp else [1]):
        if st.n_heads % mp or mp > st.n_heads:
            continue
        for pp in (_divisors(n_devices // mp) if allow_pp else [1]):
            if st.n_layers % pp:
                continue
            rest = n_devices // (mp * pp)
            for sh in (_divisors(rest) if allow_sharding else [1]):
                dp = rest // sh
                # the batch splits across both data axes; under pp it
                # must also split into whole microbatches
                data = dp * sh
                if st.batch % data:
                    continue
                if pp > 1 and st.batch % (data * (n_micro or pp)):
                    continue
                report.append(estimate(
                    st, dp, sh, mp, pp, stage=stage, n_micro=n_micro,
                    hbm_bytes=hbm, ici_bw=ici_bw, peak=peak, eff=eff))
    if not report:
        raise ValueError(
            f"tune: no mesh factorization of {n_devices} devices "
            f"satisfies the divisibility constraints (heads="
            f"{st.n_heads}, layers={st.n_layers}, batch={st.batch})")
    # prefer: feasible, lowest cost, then fewest parallel axes
    def key(c):
        axes = sum(1 for a in ("dp", "sharding", "mp", "pp")
                   if c[a] > 1)
        return (not c["feasible"], c["cost_s"], axes)
    report.sort(key=key)
    best = report[0] if report[0]["feasible"] else \
        min(report, key=lambda c: c["mem_bytes"])
    return best, report
