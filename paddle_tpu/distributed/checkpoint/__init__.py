"""Sharding-aware distributed checkpointing with reshard-on-load
(reference: the per-wrapper shard-aware state_dicts —
GroupShardedStage3.state_dict, HybridParallelOptimizer per-rank shards,
auto_parallel dist_saver — unified here per SURVEY §5.4 into ONE subsystem
like the auto-parallel dist_saver, not a per-wrapper zoo).

TPU-native design: every jax.Array already knows its sharding; ``save``
writes each process's addressable shards (one .npy per shard + a JSON
index of global shape/dtype/slices), so N hosts write N disjoint file
sets with no gather.  ``load`` assembles each target device's slab by
reading only the byte ranges that overlap it (numpy mmap) and builds the
array with ``jax.make_array_from_single_device_arrays`` under the NEW
sharding — loading into a different mesh/parallel degree (elastic resume,
TP→FSDP regrouping) is the same code path as same-mesh load.
``async_save=True`` snapshots shards to host synchronously (cheap D2H)
and writes to disk on a background thread, returning a waitable handle —
the orbax/tensorstore pattern.

Elastic resharded resume (ISSUE 14): every :func:`save_checkpoint` can
carry a **layout manifest** (``layout.manifest.json``, committed under
the same ``COMMITTED`` sentinel) recording the mesh that wrote the
checkpoint, every array's PartitionSpec, the world size, step, RNG
stream, dataloader cursor and the sharding plan that produced the
layout.  A manifest-aware load re-derives target shardings for the
*current* mesh from those axis-name specs — resuming at a different
``np`` / dp×mp split needs no caller-supplied template (PAPERS.md
"Memory-efficient array redistribution through portable collective
communication": redistribution happens at the host slab layer here,
one byte-range read per target region).
"""
import atexit
import json
import logging
import os
import re
import shutil
import threading
import time
import uuid
import zlib

import numpy as np
import jax
import jax.numpy as jnp

from ... import observability as _obs
from ...framework import failpoints as _fp
from ...framework import random as _random
from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle",
           "save_checkpoint", "latest_checkpoint", "CheckpointCorruptError",
           "build_manifest", "load_manifest", "restore_latest",
           "rng_state_from_manifest", "target_shardings_from_manifest"]

_logger = logging.getLogger("paddle_tpu.checkpoint")

_META = "checkpoint.metadata.json"
_MANIFEST = "layout.manifest.json"
_SENTINEL = "COMMITTED"               # written LAST: its presence == commit
_STEP_RE = re.compile(r"^step_(\d+)$")
_READING = ".READING."                # reader sentinel prefix (see sweep)

# failpoint sites (framework/failpoints.py): shard write, metadata write,
# the layout-manifest write, shard read, and the commit sentinel —
# `ckpt.commit_sentinel=skip` simulates a kill between the last shard
# write and the commit; `ckpt.write_manifest=error` a kill between shard
# write and manifest commit; `checkpoint.manifest_torn=skip` truncates
# the manifest mid-write (sentinel still lands: a committed step whose
# manifest is garbage); `ckpt.read_shard=delay:S` parks a reader so the
# retention-sweep race is testable deterministically
_FP_WRITE_SHARD = _fp.register("ckpt.write_shard")
_FP_WRITE_META = _fp.register("ckpt.write_meta")
_FP_WRITE_MANIFEST = _fp.register("ckpt.write_manifest")
_FP_MANIFEST_TORN = _fp.register("checkpoint.manifest_torn", skippable=True)
_FP_READ_SHARD = _fp.register("ckpt.read_shard")
_FP_COMMIT = _fp.register("ckpt.commit_sentinel", skippable=True)


class CheckpointCorruptError(ValueError):
    """A shard file failed its recorded CRC32 — the checkpoint is torn or
    bit-rotted and must not be restored from."""


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _safe(key):
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", key)


def _as_array(v):
    if isinstance(v, Tensor):
        return v._value
    return v


_pending_handles = []                 # unwaited AsyncSaveHandles
_pending_lock = threading.Lock()

_active_saves = set()                 # abspaths with an in-flight writer
_active_reads = {}                    # abspath -> live reader refcount
_active_lock = threading.Lock()       # (protects the retention sweep)


def _enter_read(path):
    """Register a live restore of ``path`` so a concurrent retention
    sweep (same process: the ``_active_reads`` refcount; other
    processes: an on-disk ``.READING.<pid>.<token>`` sentinel file)
    never deletes a committed step dir out from under it."""
    ap = os.path.abspath(path)
    with _active_lock:
        _active_reads[ap] = _active_reads.get(ap, 0) + 1
    token = os.path.join(ap, f"{_READING}{os.getpid()}."
                             f"{uuid.uuid4().hex[:8]}")
    try:
        with open(token, "w") as f:
            f.write(str(time.time_ns()))
    except OSError:
        token = None          # best effort: in-process guard still holds
    return ap, token


def _exit_read(ap, token):
    with _active_lock:
        n = _active_reads.get(ap, 0) - 1
        if n <= 0:
            _active_reads.pop(ap, None)
        else:
            _active_reads[ap] = n
    if token is not None:
        try:
            os.remove(token)
        except OSError:
            pass


def _fresh_read_sentinel(d):
    """True when ``d`` holds a fresh on-disk reader sentinel (another
    process's restore in flight).  Sentinels older than
    ``PADDLE_CKPT_READ_GRACE`` seconds (default 900) are the debris of
    a dead reader and do not pin the dir.  Lock-free: call it with
    ``_active_lock`` held when atomicity with the refcount matters."""
    try:
        names = os.listdir(d)
    except OSError:
        return False
    grace = float(os.environ.get("PADDLE_CKPT_READ_GRACE", "900"))
    now = time.time()
    for name in names:
        if not name.startswith(_READING):
            continue
        try:
            if now - os.stat(os.path.join(d, name)).st_mtime < grace:
                return True
        except OSError:
            continue
    return False


class AsyncSaveHandle:
    """Returned by save_state_dict(async_save=True).  The checkpoint is not
    loadable until the write completes (metadata is committed last, via
    atomic rename) — call ``wait()`` before relying on it.

    A background-writer exception is never silently lost: ``wait()``
    re-raises it, ``done()`` logs it once and marks the handle
    ``failed``, and an atexit drain joins + warns about any handle that
    was never waited on (an unwaited failed save means the job believes
    it has a checkpoint it does not have).
    """

    def __init__(self, target, label="checkpoint"):
        self.exception = None
        self.label = label
        self._waited = False
        self._logged = False

        def runner():
            try:
                target()
            except Exception as e:      # surfaced at wait()/done()/atexit
                self.exception = e
        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        with _pending_lock:
            _pending_handles.append(self)

    def wait(self):
        self._thread.join()
        self._waited = True
        with _pending_lock:
            if self in _pending_handles:
                _pending_handles.remove(self)
        if self.exception is not None:
            raise self.exception
        return True

    def done(self):
        finished = not self._thread.is_alive()
        if finished:
            # observing completion counts as draining: done()-polling
            # jobs must not pile handles up for the atexit sweep
            with _pending_lock:
                if self in _pending_handles:
                    _pending_handles.remove(self)
            # no log if wait() already re-raised — the caller saw it
            if self.exception is not None and not self._logged \
                    and not self._waited:
                self._logged = True
                _logger.error(
                    "async save %r failed in the background writer: %r "
                    "(the checkpoint was NOT committed)",
                    self.label, self.exception)
        return finished

    @property
    def failed(self):
        """True once the writer has finished with an exception."""
        return not self._thread.is_alive() and self.exception is not None


def _drain_pending_handles():
    with _pending_lock:
        leftovers = list(_pending_handles)
        _pending_handles.clear()
    for h in leftovers:
        h._thread.join(timeout=10.0)
        if h._thread.is_alive():
            _logger.warning(
                "async save %r still writing at interpreter exit; its "
                "checkpoint may be left uncommitted", h.label)
        elif h.exception is not None:
            _logger.warning(
                "async save %r failed and wait() was never called: %r "
                "(the checkpoint was NOT committed)", h.label, h.exception)
        else:
            _logger.warning(
                "async save %r completed but wait() was never called; "
                "call wait() before relying on the checkpoint", h.label)


atexit.register(_drain_pending_handles)


def _default_generation():
    """A save-generation id every process of one save agrees on.

    Saving into a directory that already holds rank metadata from a prior
    save with a DIFFERENT world size leaves stale rank files behind; the
    loader must not merge shard records across save generations (elastic
    resume across mesh changes would silently mix tensor data).  Single
    process: a fresh uuid.  Multi process: rank 0's uuid broadcast to all,
    so every rank stamps the same id.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        seed = np.frombuffer(uuid.uuid4().bytes[:8], dtype=np.int64)
        seed = multihost_utils.broadcast_one_to_all(seed)
        return f"{int(seed[0]) & (2**63 - 1):016x}"
    return uuid.uuid4().hex


def save_state_dict(state_dict, path, process_index=None, async_save=False,
                    generation=None, _on_commit=None):
    """Write this process's addressable shards of every array leaf.

    Layout::

        path/checkpoint.metadata.rank<P>.json  (per process, committed LAST
                                                via atomic rename — an
                                                aborted save has no
                                                metadata and fails loudly)
        path/<key>/shard_<flat_start_idx>.npy

    Keys are the flattened dotted names exactly as produced by
    ``Layer.state_dict()``; ``load_state_dict`` returns the same flat keys.
    Every process records its OWN shards in its own metadata file; the
    loader merges all rank files, so multi-host saves need no gather.

    Each save is stamped with a ``generation`` id shared by all of its
    ranks (see :func:`_default_generation`); the loader merges only the
    newest generation, so re-saving into a directory that still holds rank
    files from a larger world size cannot mix checkpoints.  Pass an
    explicit ``generation`` (e.g. the global step as a string) to override
    — all ranks must pass the same value.
    """
    t_save0 = time.perf_counter()
    if generation is None:
        if process_index is None:
            # auto mode: we know how to mint an id all ranks share
            generation = _default_generation()
        # else: explicit process_index (rank-by-rank simulation / tests)
        # with no shared id available — leave the save unstamped so the
        # per-rank files merge as one legacy generation, exactly the
        # pre-generation behavior.  Pass generation= (e.g. the step) to
        # opt into stale-file protection on this path.
    process_index = (jax.process_index() if process_index is None
                     else process_index)
    flat = {k: _as_array(v) for k, v in _flatten(state_dict).items()}
    os.makedirs(path, exist_ok=True)

    meta = {"arrays": {}, "format": 3, "saved_at_ns": time.time_ns()}
    if generation is not None:
        meta["generation"] = str(generation)
    jobs = []   # (filepath, host numpy array)
    for key, arr in flat.items():
        if not isinstance(arr, jax.Array):
            arr = jnp.asarray(arr)
        entry = {"global_shape": list(arr.shape), "dtype": str(arr.dtype),
                 "shards": []}
        is_bf16 = arr.dtype == jnp.bfloat16
        seen_starts = set()
        for shard in arr.addressable_shards:
            # replicated copies: exactly ONE owner writes (replica 0),
            # keeping multi-host file sets disjoint
            if shard.replica_id != 0:
                continue
            idx = shard.index   # tuple of slices into the global array
            starts = tuple((s.start or 0) for s in idx)
            if starts in seen_starts:
                continue
            seen_starts.add(starts)
            sizes = [
                (s.stop if s.stop is not None else arr.shape[d])
                - (s.start or 0) for d, s in enumerate(idx)]
            fname = (f"{_safe(key)}/shard_" +
                     "_".join(str(s) for s in starts) + ".npy")
            # D2H snapshot now; disk write possibly async.  bf16 has no
            # stable npy representation — store the uint16 bit pattern.
            data = np.asarray(shard.data)
            if is_bf16:
                data = data.view(np.uint16)
            # crc32 is filled in by write_all (possibly on the background
            # thread): an async save must not pay a foreground CRC pass
            rec = {"starts": list(starts), "sizes": sizes, "file": fname}
            entry["shards"].append(rec)
            jobs.append((os.path.join(path, fname), data, rec))
        meta["arrays"][key] = entry

    meta_path = os.path.join(path, f"checkpoint.metadata.rank"
                                   f"{process_index}.json")

    def write_all():
        try:
            _write_body()
        finally:
            with _active_lock:
                _active_saves.discard(os.path.abspath(path))

    def _write_body():
        for fpath, data, rec in jobs:
            if _fp._ACTIVE:
                _fp.fire(_FP_WRITE_SHARD)
            # integrity record: CRC32 of the array payload (the bytes the
            # loader will hand back), verified at load time.  Computed
            # here so it lands before the metadata commit below, off the
            # training loop for async saves.
            rec["crc32"] = _crc32_of_array(data)
            os.makedirs(os.path.dirname(fpath), exist_ok=True)
            tmp_f = f"{fpath}.tmp.{process_index}"
            with open(tmp_f, "wb") as f:   # file-object save: no .npy suffix
                np.save(f, data)
            os.replace(tmp_f, fpath)
        # commit: metadata appears only after every shard is on disk
        if _fp._ACTIVE:
            _fp.fire(_FP_WRITE_META)
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)
        if _on_commit is not None:
            _on_commit()
        # telemetry, stamped at commit: duration spans the D2H snapshot
        # through the metadata rename (async saves include their queue
        # time — that IS the save's wall cost); bytes are the host
        # payload already snapshotted, no device access here
        if _obs.enabled():
            _obs.observe("pt_checkpoint_save_ms",
                         (time.perf_counter() - t_save0) * 1e3)
            _obs.inc("pt_checkpoint_bytes_total",
                     sum(int(d.nbytes) for _, d, _ in jobs),
                     direction="save")

    # registered BEFORE the writer can run: a concurrent retention sweep
    # (an overlapping save committing out of order) must not rmtree a
    # directory this process is still writing into
    with _active_lock:
        _active_saves.add(os.path.abspath(path))
    if async_save:
        return AsyncSaveHandle(write_all, label=path)
    write_all()
    return None


def _crc32_of_array(arr):
    """CRC32 of an array's C-order payload, fed to zlib in bounded chunks
    so an mmap'd multi-GB shard never needs a full in-memory copy."""
    flat = np.ravel(arr, order="C")     # view for C-contiguous (the save
    try:                                # layout); copies only exotic cases
        byts = flat.view(np.uint8)
    except ValueError:
        return zlib.crc32(flat.tobytes())
    crc = 0
    step = 1 << 24                      # 16 MiB per crc call
    for off in range(0, byts.size, step):
        crc = zlib.crc32(byts[off:off + step], crc)
    return crc


def _verify_shard_crc(path, shard_rec, vcache):
    """Check a shard file against its recorded CRC32, once per file per
    load (vcache).  Pre-CRC checkpoints (no ``crc32`` record) pass.
    Disable wholesale with ``PADDLE_CKPT_VERIFY=0``."""
    crc_want = shard_rec.get("crc32")
    if crc_want is None or vcache is None or \
            os.environ.get("PADDLE_CKPT_VERIFY", "1") == "0":
        return
    cached = vcache.get(path)
    if cached is None:
        try:
            cached = _crc32_of_array(np.load(path, mmap_mode="r"))
        except (OSError, ValueError) as e:   # torn/truncated npy
            raise CheckpointCorruptError(
                f"checkpoint shard {path} is unreadable: {e}") from e
        vcache[path] = cached
    if cached != crc_want:
        raise CheckpointCorruptError(
            f"checkpoint shard {path} failed CRC32 verification "
            f"(recorded {crc_want:#010x}, computed {cached:#010x})")


def _read_region(path, shard_rec, region, is_bf16=False, vcache=None):
    """Read the intersection of one saved shard with a target region.

    region: list of (start, stop) in global coords.  Returns (slab_slices,
    data) where slab_slices places the data inside the target slab."""
    starts = shard_rec["starts"]
    sizes = shard_rec["sizes"]
    inter_src, inter_dst = [], []
    for d, ((rs, re_), s0, sz) in enumerate(zip(region, starts, sizes)):
        lo = max(rs, s0)
        hi = min(re_, s0 + sz)
        if lo >= hi:
            return None, None
        inter_src.append(slice(lo - s0, hi - s0))
        inter_dst.append(slice(lo - rs, hi - rs))
    if _fp._ACTIVE:
        _fp.fire(_FP_READ_SHARD)
    _verify_shard_crc(path, shard_rec, vcache)
    data = np.load(path, mmap_mode="r")[tuple(inter_src)]
    data = np.ascontiguousarray(data)
    if is_bf16:   # stored as uint16 bit pattern (see save_state_dict)
        data = data.view(jnp.bfloat16)
    return tuple(inter_dst), data


def _assemble_region(ckpt_path, entry, region, dtype, vcache=None):
    is_bf16 = entry["dtype"] == "bfloat16"
    slab = np.zeros([hi - lo for lo, hi in region], dtype)
    for shard_rec in entry["shards"]:
        dst, data = _read_region(
            os.path.join(ckpt_path, shard_rec["file"]), shard_rec, region,
            is_bf16, vcache)
        if dst is not None:
            slab[dst] = np.asarray(data).reshape(slab[dst].shape)
    return slab


def _merged_meta(path):
    """Union of the NEWEST save generation's rank metadata.

    Multi-host saves write one rank file each, all stamped with a shared
    generation id.  A directory can legitimately hold stale rank files
    from an earlier save with a larger world size (elastic resume across
    mesh changes); merging across generations would silently mix tensor
    data, so only files whose generation matches the most recently written
    one are merged.  Pre-generation (format<=2) files have no stamp and
    are treated as one legacy generation.
    """
    import glob
    files = sorted(glob.glob(os.path.join(
        path, "checkpoint.metadata.rank*.json")))
    legacy = os.path.join(path, _META)
    if not files and os.path.exists(legacy):
        files = [legacy]
    if not files:
        raise FileNotFoundError(
            f"no checkpoint metadata under {path} — incomplete/aborted "
            "save, or wrong directory")
    metas = []
    for fp in files:
        with open(fp) as f:
            meta = json.load(f)
        m = re.search(r"rank(\d+)", os.path.basename(fp))
        rank = int(m.group(1)) if m else 0
        metas.append((meta.get("generation"), rank, meta))
    # The current generation is whatever the LOWEST-rank file carries:
    # every save includes process 0, so a re-save always rewrites the
    # lowest rank file, while wallclock stamps are cross-host clocks and
    # can make a stale higher-rank file look newest.
    newest_gen = min(metas, key=lambda m: m[1])[0]
    selected = [m for gen, _, m in metas if gen == newest_gen]
    merged = {"arrays": {}}
    for meta in selected:
        for key, entry in meta["arrays"].items():
            cur = merged["arrays"].get(key)
            if cur is None:
                merged["arrays"][key] = {
                    "global_shape": entry["global_shape"],
                    "dtype": entry["dtype"],
                    "shards": list(entry["shards"])}
            else:
                seen = {tuple(s["starts"]) for s in cur["shards"]}
                cur["shards"].extend(
                    s for s in entry["shards"]
                    if tuple(s["starts"]) not in seen)
    return merged


# -- layout manifest (elastic resharded resume) -------------------------
#
# ``layout.manifest.json`` sits beside the rank metadata in a step dir
# and is committed under the same COMMITTED sentinel (process 0 writes
# it strictly before the sentinel).  It records everything a relaunched
# job needs to resume on a DIFFERENT topology: the mesh that wrote the
# checkpoint, per-array PartitionSpecs (axis *names*, which survive a
# mesh-shape change), world size, step, the RNG stream, the dataloader
# cursor, and the sharding plan that produced the layout.

def _spec_to_json(spec, ndim):
    """PartitionSpec -> JSON list, one entry per dim (None | name |
    [names]), padded to the array's rank."""
    entries = []
    for e in tuple(spec):
        if e is None:
            entries.append(None)
        elif isinstance(e, (tuple, list)):
            entries.append([str(a) for a in e])
        else:
            entries.append(str(e))
    entries += [None] * (ndim - len(entries))
    return entries[:ndim]


def _adapt_spec(entries, mesh, global_shape):
    """Re-derive a PartitionSpec for the CURRENT mesh from saved axis
    names: axes the new mesh doesn't have are dropped (replicate), and
    a dim that stops dividing evenly under the new axis sizes falls
    back to replicated on that dim — elastic resume must never refuse
    a legal mesh over a divisibility corner."""
    from jax.sharding import PartitionSpec
    out = []
    for d, e in enumerate(entries or ()):
        if d >= len(global_shape):
            break
        names = [e] if isinstance(e, str) else list(e or ())
        names = [n for n in names if n in mesh.axis_names]
        total = 1
        for n in names:
            total *= int(mesh.shape[n])
        if not names or total <= 0 or global_shape[d] % total:
            out.append(None)
        elif len(names) == 1:
            out.append(names[0])
        else:
            out.append(tuple(names))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def _mesh_desc(mesh):
    return {"axis_names": [str(a) for a in mesh.axis_names],
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names]}


def _plan_desc(plan):
    if plan is None:
        return None
    gc = getattr(plan, "grad_comm", None)
    return {"level": plan.level,
            "fsdp_axis": plan.fsdp_axis,
            "mp_axis": plan.mp_axis,
            "batch_axes": list(plan.batch_axes or ()),
            "zero1": bool(gc is not None and getattr(gc, "zero1", False))}


def build_manifest(state_dict, step=None, plan=None, mesh=None,
                   data_cursor=None, opt_meta=None, rng=True, extra=None):
    """Capture the layout manifest for ``state_dict`` as it is placed
    RIGHT NOW: per-array PartitionSpecs from the live shardings, the
    mesh (explicit ``mesh`` > ``plan.mesh`` > the first NamedSharding
    seen), world size, RNG stream (the global key chain every rank
    folds per-shard keys from — one record restores any np), plus the
    caller's dataloader cursor and optimizer metadata."""
    from jax.sharding import NamedSharding
    flat = {k: _as_array(v) for k, v in _flatten(state_dict).items()}
    pspecs = {}
    cap_mesh = None
    for key, arr in flat.items():
        sh = getattr(arr, "sharding", None)
        if isinstance(sh, NamedSharding):
            pspecs[key] = _spec_to_json(sh.spec, getattr(arr, "ndim", 0))
            if cap_mesh is None:
                cap_mesh = sh.mesh
    m = mesh if mesh is not None else (
        plan.mesh if plan is not None else cap_mesh)
    manifest = {
        "format": 1,
        "step": int(step) if step is not None else None,
        "world_size": int(m.size) if m is not None else jax.device_count(),
        "mesh": _mesh_desc(m) if m is not None else None,
        "pspecs": pspecs,
        "plan": _plan_desc(plan),
        "data_cursor": data_cursor,
        "opt": opt_meta or {},
        "extra": extra or {},
    }
    if rng:
        key = _random.get_rng_state()[0]
        manifest["rng"] = {
            "seed": _random.get_seed(),
            "key_data": np.asarray(jax.random.key_data(key))
                          .astype(np.uint32).tolist(),
        }
    return manifest


def load_manifest(step_dir):
    """The step dir's layout manifest, or None when absent/unreadable.
    An unreadable manifest degrades to the template-path restore (the
    pre-manifest contract) instead of failing the whole resume."""
    p = os.path.join(step_dir, _MANIFEST)
    try:
        with open(p) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        _logger.warning(
            "layout manifest %s is unreadable (%s); falling back to the "
            "template restore path", p, e)
        return None


def rng_state_from_manifest(manifest):
    """Rebuild the saved global PRNG key, or None when unrecorded."""
    rng = (manifest or {}).get("rng") or {}
    data = rng.get("key_data")
    if data is None:
        return None
    return jax.random.wrap_key_data(
        jnp.asarray(np.asarray(data, dtype=np.uint32)))


def target_shardings_from_manifest(manifest, mesh, shapes):
    """{flat key -> NamedSharding on ``mesh``} re-derived from the
    manifest's saved PartitionSpecs.  ``shapes``: {key -> global shape}
    (divisibility decides which saved axes survive)."""
    from jax.sharding import NamedSharding
    out = {}
    for key, entries in (manifest.get("pspecs") or {}).items():
        if key not in shapes:
            continue
        out[key] = NamedSharding(
            mesh, _adapt_spec(entries, mesh, tuple(shapes[key])))
    return out


def _detect_reshard(manifest, mesh, tmpl_flat):
    """(old_np, new_np) when the restore target topology differs from
    the one that wrote the checkpoint, else None.  The current topology
    is the explicit ``mesh`` or the first NamedSharding in the
    template."""
    if not manifest or manifest.get("mesh") is None:
        return None
    cur = mesh
    if cur is None:
        from jax.sharding import NamedSharding
        for v in (tmpl_flat or {}).values():
            sh = getattr(v, "sharding", None)
            if isinstance(sh, NamedSharding):
                cur = sh.mesh
                break
    if cur is None:
        return None
    old_np = int(manifest.get("world_size") or 0)
    new_np = int(cur.size)
    if old_np and (old_np != new_np or
                   _mesh_desc(cur) != manifest["mesh"]):
        return old_np, new_np
    return None


def _emit_reshard(old_np, new_np, root, source):
    """elastic_reshard guardian event + pt_checkpoint_reshard_total —
    the observable record that a checkpoint crossed a topology change."""
    if _obs.enabled():
        _obs.inc("pt_checkpoint_reshard_total", kind=source)
    try:
        from ...framework import guardian as _guardian
        _guardian.emit("elastic_reshard", old_np=int(old_np),
                       new_np=int(new_np), root=str(root),
                       source=source)
    except Exception:           # guardian unavailable in exotic embeds
        _logger.info("elastic reshard: np %s -> %s (%s)", old_np, new_np,
                     source)


def _emit_fallback(root, step, kind, detail):
    """checkpoint_fallback guardian event + the fallback counter: a
    resume that silently lost steps must be observable."""
    _obs.inc("pt_checkpoint_fallbacks_total", kind=kind)
    try:
        from ...framework import guardian as _guardian
        _guardian.emit("checkpoint_fallback", root=str(root),
                       step=int(step), kind=kind, detail=str(detail))
    except Exception:
        _logger.info("checkpoint fallback at %s step %s (%s): %s", root,
                     step, kind, detail)


def load_state_dict(path, template=None, shardings=None, mesh=None):
    """Load a checkpoint, resharding every array onto its target sharding.

    Returns a FLAT dict keyed exactly as saved (dotted Layer.state_dict
    names round-trip into ``set_state_dict`` unchanged).  Target selection,
    in priority order: ``shardings`` (flat-key → jax.sharding.Sharding),
    the sharding of the same-keyed array in ``template`` (a state_dict of
    arrays/Tensors laid out how the caller wants them), or
    fully-replicated on ``mesh``/default device.  Loading into a different
    mesh shape than the save ran on is the normal case, not an error.

    Integrity: every shard file read is checked against the CRC32 the
    saver recorded; a mismatch raises :class:`CheckpointCorruptError`.
    When ``path`` is a checkpoint ROOT (holding ``step_NNNN`` children
    from :func:`save_checkpoint` rather than metadata itself), the
    newest committed step is loaded, falling back step by step past any
    torn or corrupt checkpoint until one restores cleanly.

    Elastic reshard: when the step dir carries a layout manifest and a
    target ``mesh`` is given, arrays with no explicit sharding/template
    get their target re-derived from the manifest's saved PartitionSpecs
    adapted to the current mesh — restoring onto a different np or
    dp×mp split needs no caller-supplied template.  A topology change
    emits the ``elastic_reshard`` guardian event and books
    ``pt_checkpoint_reshard_*``.
    """
    if _is_checkpoint_root(path):
        return _load_latest_valid(path, template=template,
                                  shardings=shardings, mesh=mesh)
    return _load_step_dir(path, template, shardings, mesh)[0]


def _load_step_dir(path, template=None, shardings=None, mesh=None):
    """One step dir → ``(state, manifest)``.  The manifest is parsed
    INSIDE the reader-sentinel window — callers that need it must not
    re-read it from disk after the sentinel is released (a concurrent
    retention sweep could have removed the dir by then)."""
    t_load0 = time.perf_counter()
    # reader sentinel: a concurrent retention sweep (overlapping async
    # save committing a newer step) must never rmtree this dir mid-read
    ap, rtok = _enter_read(path)
    try:
        vcache = {}
        meta = _merged_meta(path)
        tmpl_flat = ({k: _as_array(v) for k, v in
                      _flatten(template).items()}
                     if template is not None else {})
        manifest = load_manifest(path)
        derived = {}
        if manifest is not None and mesh is not None:
            shapes = {k: tuple(e["global_shape"])
                      for k, e in meta["arrays"].items()}
            derived = target_shardings_from_manifest(manifest, mesh,
                                                     shapes)
        reshard = _detect_reshard(manifest, mesh, tmpl_flat)
        out = {}
        for key, entry in meta["arrays"].items():
            shape = tuple(entry["global_shape"])
            dtype = np.dtype(entry["dtype"]) \
                if entry["dtype"] != "bfloat16" else jnp.bfloat16
            target = None
            if shardings is not None and key in shardings:
                target = shardings[key]
            elif key in tmpl_flat and isinstance(tmpl_flat[key],
                                                 jax.Array):
                target = tmpl_flat[key].sharding
            elif key in derived:
                target = derived[key]
            if target is None:
                full = _assemble_region(
                    path, entry, [(0, s) for s in shape], dtype, vcache)
                arr = jnp.asarray(full)
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec
                    arr = jax.device_put(
                        arr, NamedSharding(mesh, PartitionSpec()))
                out[key] = arr
                continue
            # build per-device slabs for the target sharding; devices
            # sharing a region (replication) reuse one host slab
            device_map = target.addressable_devices_indices_map(shape)
            slab_cache = {}
            slabs = []
            for dev, idx in device_map.items():
                region = []
                for d, s in enumerate(idx):
                    start = s.start or 0
                    stop = s.stop if s.stop is not None else shape[d]
                    region.append((start, stop))
                rkey = tuple(region)
                if rkey not in slab_cache:
                    slab_cache[rkey] = _assemble_region(
                        path, entry, region, dtype, vcache)
                slabs.append(jax.device_put(slab_cache[rkey], dev))
            out[key] = jax.make_array_from_single_device_arrays(
                shape, target, slabs)
    finally:
        _exit_read(ap, rtok)
    if reshard is not None:
        _emit_reshard(reshard[0], reshard[1], path, "load")
        if _obs.enabled():
            _obs.observe("pt_checkpoint_reshard_ms",
                         (time.perf_counter() - t_load0) * 1e3)
    if _obs.enabled():
        _obs.observe("pt_checkpoint_load_ms",
                     (time.perf_counter() - t_load0) * 1e3)
        nbytes = 0
        for entry in meta["arrays"].values():
            n = 1
            for d in entry["global_shape"]:
                n *= int(d)
            itemsize = (2 if entry["dtype"] == "bfloat16"
                        else np.dtype(entry["dtype"]).itemsize)
            nbytes += n * itemsize
        _obs.inc("pt_checkpoint_bytes_total", nbytes, direction="load")
    return out, manifest


# -- step-directory commit protocol (save_checkpoint / latest) ----------
#
# Layout under a checkpoint ROOT::
#
#     root/step_00000042/<shards + rank metadata>   (save_state_dict)
#     root/step_00000042/COMMITTED                  (sentinel, written LAST)
#
# A step directory without the sentinel is torn (the writer died between
# shard write and commit) and is never restored from.  Retention keeps
# the newest K committed steps; older ones — and torn directories older
# than the newest commit — are swept after each successful commit.

def _step_path(root, step):
    return os.path.join(root, f"step_{int(step):08d}")


def _iter_steps(root):
    """[(step, dirpath, committed)] sorted by step ascending."""
    try:
        names = os.listdir(root)
    except (FileNotFoundError, NotADirectoryError):
        return []
    out = []
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        d = os.path.join(root, name)
        if os.path.isdir(d):
            out.append((int(m.group(1)), d,
                        os.path.exists(os.path.join(d, _SENTINEL))))
    out.sort()
    return out


def _is_checkpoint_root(path):
    """A directory holding step_NNNN children but no metadata of its own."""
    if os.path.exists(os.path.join(path, _META)):
        return False
    import glob
    if glob.glob(os.path.join(path, "checkpoint.metadata.rank*.json")):
        return False
    return bool(_iter_steps(path))


def latest_checkpoint(root):
    """Path of the newest COMMITTED step directory under ``root``, or
    None.  Torn (uncommitted) directories are skipped — they are the
    debris of a writer that died mid-save."""
    for step, d, committed in reversed(_iter_steps(root)):
        if committed:
            return d
    return None


def restore_latest(root, template=None, shardings=None, mesh=None):
    """Newest committed checkpoint under ``root`` that actually
    restores, falling back past torn and corrupt steps — each skipped
    step emits a ``checkpoint_fallback`` guardian event (plus the
    ``pt_checkpoint_fallbacks_total`` counter), so a resume that lost
    steps is observable, never silent.

    Returns ``(state, manifest, step_dir)``; ``manifest`` is None for
    pre-manifest checkpoints."""
    entries = list(reversed(_iter_steps(root)))
    steps = [(s, d) for s, d, committed in entries if committed]
    torn = [(s, d) for s, d, committed in entries if not committed]
    if not steps:
        # nothing restorable at all: every torn dir is lost work
        for s, d in torn:
            _emit_fallback(root, s, "torn",
                           f"uncommitted step dir {d} skipped")
        raise FileNotFoundError(
            f"no committed checkpoint under {root} — nothing to resume "
            "from (torn step directories, if any, were skipped)")
    last_err = None
    for step, d in steps:
        try:
            # one pass: the manifest comes back from the same reader-
            # pinned window as the state (re-reading it here, after the
            # sentinel is gone, could race a retention sweep)
            state, manifest = _load_step_dir(d, template=template,
                                             shardings=shardings,
                                             mesh=mesh)
            # book only torn dirs NEWER than the restored step: those
            # are writer-died-mid-save steps this resume actually lost.
            # Older torn debris cost the resume nothing, and a dir this
            # process's async writer is STILL FILLING is an in-flight
            # save, not lost work — booking either would make the event
            # unusable for alerting.
            with _active_lock:
                in_flight = set(_active_saves)
            for s, td in torn:
                if s > step and os.path.abspath(td) not in in_flight:
                    _emit_fallback(root, s, "torn",
                                   f"uncommitted step dir {td} skipped")
            return state, manifest, d
        # only integrity failures trigger fallback: CRC mismatch, files
        # lost from under the sentinel, truncated metadata.  A user error
        # (wrong template/sharding) raises through immediately rather
        # than being masked as K successive "corrupt" checkpoints.
        except (CheckpointCorruptError, FileNotFoundError, OSError,
                json.JSONDecodeError) as e:
            _logger.warning(
                "checkpoint %s is unusable (%s); falling back to the "
                "previous one", d, e)
            _emit_fallback(root, step, "corrupt", e)
            last_err = e
    raise CheckpointCorruptError(
        f"every committed checkpoint under {root} failed to restore "
        f"(last error: {last_err})") from last_err


def _load_latest_valid(root, **kw):
    """State-only veneer over :func:`restore_latest` (the historical
    root-load entry point load_state_dict delegates to)."""
    return restore_latest(root, **kw)[0]


def _retention_sweep(root, keep_last):
    """Delete all but the newest ``keep_last`` committed steps, plus torn
    directories older than the newest commit (debris of dead writers).
    Directories this process is still writing into (overlapping async
    saves, which can commit out of order) are exempt via the
    ``_active_saves`` registry; torn dirs newer than the commit are left
    alone too — another host's save may be filling them.  Directories a
    restore is reading FROM right now (same process: ``_active_reads``;
    any process: a fresh ``.READING.*`` sentinel file) are likewise
    never swept — an elastic resume restoring the K-th-newest step must
    not lose it to a concurrent writer's sweep mid-read.  The reader
    check and an atomic rename out of the ``step_NNNN`` namespace
    happen under ONE ``_active_lock`` hold per dir (not check-then-act);
    the slow rmtree runs on the renamed dir outside the lock, so
    registration never stalls behind disk I/O.  A same-process reader
    either registers before the sweep takes the lock and pins the dir,
    or registers after the rename and falls back to a newer step
    through the normal corrupt-fallback path.  (Cross-process, the
    sentinel-file check leaves an inherent listdir-vs-token-write
    window; the grace period and the never-doomed newest-K cover
    practical readers.)"""
    if not keep_last or keep_last <= 0:
        return
    steps = _iter_steps(root)
    committed = [(s, d) for s, d, ok in steps if ok]
    doomed = [d for s, d in committed[:-keep_last]]
    if committed:
        newest_committed = committed[-1][0]
        doomed += [d for s, d, ok in steps
                   if not ok and s < newest_committed]
    for d in doomed:
        ap = os.path.abspath(d)
        # the cross-process sentinel-file check is inherently racy, so
        # its listdir/stat runs OUTSIDE the lock (no disk I/O stalls
        # registration); the in-process refcount check + the ATOMIC
        # rename out of the step_NNNN namespace share ONE lock hold —
        # that pair is what makes the same-process guarantee sound.
        # The slow rmtree of a multi-GB dir runs outside the lock.
        if _fresh_read_sentinel(d):
            continue
        tomb = f"{d}.doomed.{os.getpid()}.{uuid.uuid4().hex[:6]}"
        with _active_lock:
            if ap in _active_saves or _active_reads.get(ap):
                continue
            try:
                os.rename(d, tomb)
            except OSError as e:
                _logger.warning(
                    "retention sweep could not retire %s: %s", d, e)
                continue
        try:
            shutil.rmtree(tomb)
        except OSError as e:
            _logger.warning("retention sweep could not remove %s: %s",
                            tomb, e)
    # orphaned tombs (an earlier sweep's rmtree failed transiently —
    # NFS EBUSY, open handle): they no longer match _STEP_RE, so
    # collect them here or they would accumulate forever
    try:
        leftovers = [n for n in os.listdir(root)
                     if ".doomed." in n and n.startswith("step_")]
    except OSError:
        leftovers = []
    for name in leftovers:
        p = os.path.join(root, name)
        if os.path.isdir(p):
            try:
                shutil.rmtree(p)
            except OSError as e:
                _logger.warning(
                    "retention sweep could not remove %s: %s", p, e)


def save_checkpoint(state_dict, root, step, process_index=None,
                    async_save=False, keep_last=None, manifest=None):
    """Save into ``root/step_NNNN`` with crash-safe commit + retention.

    The commit sentinel is written by process 0 only, strictly after its
    shards and metadata are on disk (multi-host note: process 0 commits
    for the job, so call this after a cross-host barrier if stragglers
    are possible).  ``keep_last`` (default: env ``PADDLE_CKPT_KEEP_LAST``,
    else 5; 0 disables) sweeps older committed steps after the commit.
    Returns the step directory path (sync) or an :class:`AsyncSaveHandle`
    whose ``wait()`` completes after commit + sweep (async).

    ``manifest`` (a :func:`build_manifest` dict, or True to capture one
    from the state's live shardings) is written as
    ``layout.manifest.json`` strictly before the sentinel, so a
    committed step always carries a complete manifest — the elastic
    resharded-resume contract.
    """
    if keep_last is None:
        keep_last = int(os.environ.get("PADDLE_CKPT_KEEP_LAST", "5"))
    path = _step_path(root, step)
    pidx = (jax.process_index() if process_index is None else process_index)
    if manifest is True:
        # only process 0 writes the manifest — other ranks must not pay
        # the state walk + key_data readback for a dict commit() discards
        manifest = build_manifest(state_dict, step=step) if pidx == 0 \
            else None
    # re-saving an already-committed step: UN-commit it first, or a
    # crash mid-rewrite would leave a committed-looking dir with torn
    # shards — the one state the sentinel-written-LAST protocol exists
    # to make impossible.  Torn-until-recommitted is the honest state.
    if pidx == 0:
        try:
            os.remove(os.path.join(path, _SENTINEL))
        except FileNotFoundError:
            pass

    def commit():
        if pidx != 0:
            return
        if _fp._ACTIVE and _fp.fire(_FP_COMMIT) == "skip":
            return          # simulated kill between shard write and commit
        if manifest is not None:
            man = dict(manifest)
            man.setdefault("format", 1)
            man["step"] = int(step)
            if _fp._ACTIVE:
                # a kill between shard write and manifest commit leaves
                # NO sentinel — the whole dir reads as torn and resume
                # falls back cleanly (chaos-tested)
                _fp.fire(_FP_WRITE_MANIFEST)
            payload = json.dumps(man)
            if _fp._ACTIVE and _fp.fire(_FP_MANIFEST_TORN) == "skip":
                # simulate a torn manifest write that still got
                # committed (crash straddling a non-atomic filesystem):
                # the loader must degrade to the template path
                payload = payload[:max(8, len(payload) // 3)]
            mtmp = os.path.join(path, _MANIFEST + ".tmp")
            with open(mtmp, "w") as f:
                f.write(payload)
            os.replace(mtmp, os.path.join(path, _MANIFEST))
        # overlapping async saves can commit out of order, and the later
        # step's retention sweep may then remove this still-uncommitted
        # directory mid-write; never stamp COMMITTED unless everything we
        # just wrote is actually present
        meta_p = os.path.join(
            path, f"checkpoint.metadata.rank{pidx}.json")
        try:
            with open(meta_p) as f:
                written = json.load(f)
            missing = [
                s["file"] for e in written["arrays"].values()
                for s in e["shards"]
                if not os.path.exists(os.path.join(path, s["file"]))]
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorruptError(
                f"refusing to commit {path}: metadata unreadable ({e}) — "
                "was the directory swept by a concurrent save?") from e
        if missing:
            raise CheckpointCorruptError(
                f"refusing to commit {path}: shard file(s) {missing} "
                "vanished before the sentinel write (swept by a "
                "concurrent save?)")
        tmp = os.path.join(path, _SENTINEL + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"step": int(step),
                       "committed_at_ns": time.time_ns()}, f)
        os.replace(tmp, os.path.join(path, _SENTINEL))
        _retention_sweep(root, keep_last)

    handle = save_state_dict(state_dict, path, process_index=process_index,
                             async_save=async_save,
                             generation=str(int(step)), _on_commit=commit)
    return handle if async_save else path
