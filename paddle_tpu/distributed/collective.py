"""Communication API (reference: python/paddle/distributed/communication/
over ProcessGroupNCCL — paddle/fluid/distributed/collective/).

TPU-native: the transport is XLA collectives over ICI/DCN.  Inside a
``shard_map``/``pjit`` trace these functions lower to ``lax.psum`` /
``all_gather`` / ``all_to_all`` / ``ppermute`` on the named mesh axis; in
eager single-process mode they are the world-size-1 identity (matching the
reference's behavior when nranks==1).  Async ``Task`` semantics come free
from XLA's async collectives, so ``wait`` is a barrier on the value.

Groups name mesh axes rather than holding NCCL communicators: ``new_group``
returns a ``Group`` carrying the axis name(s) the collective should ride.
"""
import time

import jax
import jax.numpy as jnp
from jax import lax

from .. import observability as _obs
from ..framework.core import Tensor
from ..framework.autograd import call_op
from ..framework import failpoints as _fp
from ..framework import guardian as _guardian
from .env import get_world_size

# failpoint inside the watchdog-guarded barrier body: `delay:T` with a
# smaller barrier timeout simulates a straggler deterministically
_FP_BARRIER = _fp.register("collective.barrier")


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group ≙ one or more mesh axis names.  ``timeout``
    (seconds) is the watchdog deadline for this group's blocking
    host-level ops (``barrier``, value ``wait``); None = unmonitored."""

    def __init__(self, axis_name=None, ranks=None, group_id=0,
                 timeout=None):
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.id = group_id
        self.nranks = len(self.ranks) if self.ranks else None
        self.timeout = timeout

    @property
    def world_size(self):
        if self.nranks:
            return self.nranks
        return get_world_size()

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        if self.ranks:
            return self.ranks.index(rank) if rank in self.ranks else -1
        return rank

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


_GROUPS = {}
_GROUP_COUNTER = [0]
_WORLD = Group(axis_name=None, group_id=0)


def _in_named_trace(axis):
    """True if `axis` is a bound mapped axis (inside shard_map/pmap)."""
    if axis is None:
        return False
    try:
        lax.axis_index(axis)  # raises NameError outside a binding context
        return True
    except (NameError, Exception):
        return False


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    # timeout lands on the Group (it used to be accepted and silently
    # dropped) and is honored by the guardian watchdog in barrier()/wait()
    if timeout is not None and hasattr(timeout, "total_seconds"):
        timeout = timeout.total_seconds()    # datetime.timedelta compat
    _GROUP_COUNTER[0] += 1
    g = Group(axis_name=axis_name, ranks=ranks,
              group_id=_GROUP_COUNTER[0], timeout=timeout)
    _GROUPS[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _WORLD
    return _GROUPS.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _GROUPS.clear()
    else:
        _GROUPS.pop(group.id, None)


def _axis_of(group):
    if group is None:
        return None
    return group.axis_name


def _apply(x, fn):
    """Run fn over a Tensor through the tape (collectives are
    autograd-aware: psum's transpose is psum etc., handled by jax)."""
    if isinstance(x, Tensor):
        return call_op(fn, x)
    return Tensor(fn(jnp.asarray(x)))


def _telemetry(op, *vals):
    """Per-op call/byte counters (``pt_collective_*``).  Payload size
    comes from static ``.shape``/``.dtype`` metadata ONLY, so this is
    legal under tracing (no readback — the tracer-safety taint stops at
    shape/dtype).  Inside a jit trace the counters tick per *tracing*,
    not per execution; the catalog documents that honestly.  Latency is
    recorded only for the host-blocking ops (barrier/wait) — a traced
    collective has no host-observable duration."""
    if not _obs.enabled():
        return
    nbytes = 0
    for v in vals:
        for t in (v if isinstance(v, (list, tuple)) else (v,)):
            t = getattr(t, "_value", t)
            shape = getattr(t, "shape", None)
            dtype = getattr(t, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            nbytes += n * jnp.dtype(dtype).itemsize
    _obs.inc("pt_collective_calls_total", op=op)
    if nbytes:
        _obs.inc("pt_collective_bytes_total", nbytes, op=op)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("all_reduce", f"op={op} axis={_axis_of(group)}")
    _telemetry("all_reduce", tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        red = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
               ReduceOp.MIN: lax.pmin,
               ReduceOp.AVG: lambda v, a: lax.pmean(v, a)}[op]
        out = _apply(tensor, lambda v: red(v, axis))
    else:
        out = tensor  # world of 1 (or replicated eager value): identity
    if isinstance(tensor, Tensor) and isinstance(out, Tensor) \
            and out is not tensor:
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # On an SPMD mesh every shard computes the reduction (XLA has no
    # rooted reduce); semantically equivalent for the framework's uses.
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("all_gather", f"axis={_axis_of(group)}")
    _telemetry("all_gather", tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(tensor, lambda v: lax.all_gather(v, axis))
        n = out.shape[0]
        parts = [out[i] for i in range(n)]
    else:
        parts = [tensor]
    tensor_list.clear()
    tensor_list.extend(parts)
    return _Task(tensor_list)


def all_gather_object(object_list, obj, group=None):
    object_list.clear()
    object_list.append(obj)
    return _Task(object_list)


def all_gather_into_tensor(out_tensor, tensor, group=None, sync_op=True,
                           concat_axis=0):
    if _guardian._TRACK:
        _guardian.record_op("all_gather_into_tensor",
                            f"axis={_axis_of(group)}")
    _telemetry("all_gather_into_tensor", tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(tensor, lambda v: lax.all_gather(
            v, axis, tiled=True, axis=concat_axis))
    else:
        out = tensor
    out_tensor._value = out._value
    out_tensor._node = out._node
    out_tensor._out_idx = out._out_idx
    out_tensor.stop_gradient = out.stop_gradient
    return _Task(out_tensor)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("reduce_scatter", f"axis={_axis_of(group)}")
    _telemetry("reduce_scatter", tensor_or_tensor_list)
    axis = _axis_of(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat
        src = concat(list(src), axis=0)
    if axis is not None and _in_named_trace(axis):
        out = _apply(src, lambda v: lax.psum_scatter(
            v, axis, scatter_dimension=0, tiled=True))
    else:
        out = src
    tensor._value = out._value
    tensor._node = out._node
    tensor._out_idx = out._out_idx
    tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("alltoall", f"axis={_axis_of(group)}")
    _telemetry("alltoall", in_tensor_list)
    axis = _axis_of(group)
    from ..tensor.manipulation import stack
    x = stack(list(in_tensor_list), axis=0)
    if axis is not None and _in_named_trace(axis):
        out = _apply(x, lambda v: lax.all_to_all(
            v, axis, split_axis=0, concat_axis=0, tiled=False))
        parts = [out[i] for i in range(out.shape[0])]
    else:
        parts = list(in_tensor_list)
    out_tensor_list.clear()
    out_tensor_list.extend(parts)
    return _Task(out_tensor_list)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("alltoall_single", f"axis={_axis_of(group)}")
    _telemetry("alltoall_single", in_tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(in_tensor, lambda v: lax.all_to_all(
            v, axis, split_axis=0, concat_axis=0, tiled=True))
    else:
        out = in_tensor
    out_tensor._value = out._value
    out_tensor._node = out._node
    out_tensor._out_idx = out._out_idx
    out_tensor.stop_gradient = out.stop_gradient
    return _Task(out_tensor)


def broadcast(tensor, src=0, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("broadcast", f"axis={_axis_of(group)}")
    _telemetry("broadcast", tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        # select src rank's shard everywhere via all_gather + index
        out = _apply(tensor, lambda v: lax.all_gather(v, axis)[src])
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    return _Task(tensor)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _guardian._TRACK:
        _guardian.record_op("scatter", f"axis={_axis_of(group)}")
    _telemetry("scatter", tensor)
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis) and tensor_list:
        from ..tensor.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)
        idx = lax.axis_index(axis)
        out = _apply(stacked, lambda v: v[idx])
        tensor._value = out._value
        tensor._node = out._node
        tensor._out_idx = out._out_idx
        tensor.stop_gradient = out.stop_gradient
    elif tensor_list:
        tensor._value = tensor_list[src]._value
    return _Task(tensor)


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv are not exposed eagerly on TPU; use "
        "paddle_tpu.distributed.p2p.ppermute inside a shard_map (the "
        "pipeline runtime does this), or batch_isend_irecv")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv are not exposed eagerly on TPU; use "
        "paddle_tpu.distributed.p2p.ppermute inside a shard_map")


def ppermute(tensor, perm, group=None):
    """P2P as collective-permute (TPU's native send/recv). perm: list of
    (src, dst) pairs; must run inside shard_map on the group's axis."""
    axis = _axis_of(group)
    return _apply(tensor, lambda v: lax.ppermute(v, axis, perm))


def barrier(group=None, timeout=None):
    """Cross-process barrier.  ``timeout`` (seconds; default: the
    group's ``new_group(timeout=...)``) runs the wait under the guardian
    watchdog — on expiry a ``watchdog_timeout`` guardian-log event dumps
    the last-op-seen ring and a clear ``TimeoutError``
    (:class:`guardian.CollectiveTimeout`) is raised instead of a silent
    hang."""
    if timeout is None and group is not None:
        timeout = getattr(group, "timeout", None)

    def _body():
        # XLA programs are bulk-synchronous; an explicit barrier is only
        # meaningful across processes.
        if _fp._ACTIVE:
            _fp.fire(_FP_BARRIER)
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("paddle_tpu_barrier")

    t0 = time.perf_counter()
    try:
        if timeout is not None:
            _guardian.run_with_deadline(_body, timeout, "barrier",
                                        f"group={getattr(group, 'id', 0)}")
        else:
            if _guardian._TRACK:
                _guardian.record_op("barrier",
                                    f"group={getattr(group, 'id', 0)}")
            _body()
    finally:
        # host-blocking op: wall latency is observable without any
        # device readback (recorded on timeout/error paths too — a
        # stuck barrier's duration is the interesting sample)
        if _obs.enabled():
            _obs.inc("pt_collective_calls_total", op="barrier")
            _obs.observe("pt_collective_latency_ms",
                         (time.perf_counter() - t0) * 1e3, op="barrier")


def wait(tensor, group=None, use_calc_stream=True, timeout=None):
    if timeout is None and group is not None:
        timeout = getattr(group, "timeout", None)
    if isinstance(tensor, Tensor):
        def _body():
            try:
                tensor._value.block_until_ready()
            except Exception:
                pass
        t0 = time.perf_counter()
        try:
            if timeout is not None:
                _guardian.run_with_deadline(_body, timeout, "wait",
                                            f"shape={tuple(tensor.shape)}")
            else:
                _body()
        finally:
            if _obs.enabled():
                _obs.inc("pt_collective_calls_total", op="wait")
                _obs.observe("pt_collective_latency_ms",
                             (time.perf_counter() - t0) * 1e3, op="wait")


class _Task:
    def __init__(self, result):
        self._result = result

    def wait(self):
        if isinstance(self._result, Tensor):
            wait(self._result)
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        self.wait()


class stream:
    """paddle.distributed.stream.* compat namespace."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
    reduce = staticmethod(reduce)


def broadcast_object_list(object_list, src=0, group=None):
    """reference: paddle.distributed.broadcast_object_list.  Single-
    controller SPMD runs one Python process per host with a shared
    program, so the source rank's objects are already what every rank
    holds; multi-host exchange rides the TCP store."""
    import jax
    if jax.process_count() > 1:
        # two-phase broadcast (size then padded bytes) so shapes agree on
        # every host; multihost broadcast sources process 0
        if src != 0:
            raise NotImplementedError(
                "broadcast_object_list: multi-host broadcast sources "
                "process 0 (jax multihost_utils); re-root your objects "
                "or use the TCP store for arbitrary-src exchange")
        from jax.experimental import multihost_utils
        import numpy as _np
        import pickle
        payload = _np.frombuffer(
            pickle.dumps(list(object_list)), dtype=_np.uint8)
        size = int(multihost_utils.broadcast_one_to_all(
            _np.asarray([payload.size], _np.int32))[0])
        buf = _np.zeros((size,), _np.uint8)
        buf[:min(payload.size, size)] = payload[:size]
        synced = multihost_utils.broadcast_one_to_all(buf)
        object_list[:] = pickle.loads(bytes(_np.asarray(synced)))
    return _Task(object_list)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """reference: paddle.distributed.scatter_object_list."""
    from .env import get_rank, get_world_size
    rank = group.get_group_rank(get_rank()) if group is not None and \
        hasattr(group, "get_group_rank") else get_rank()
    n = (group.nranks if group is not None and
         getattr(group, "nranks", None) else get_world_size())
    out_object_list.clear()
    if in_object_list:
        if len(in_object_list) < n:
            raise ValueError(
                f"scatter_object_list: {len(in_object_list)} objects for "
                f"{n} ranks")
        out_object_list.append(in_object_list[rank])
    return _Task(out_object_list)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """reference: paddle.distributed.gather — collect shards to dst.  In
    the SPMD trace every rank computes the gathered list (XLA all_gather;
    dst selection is a no-op on a single program)."""
    axis = _axis_of(group)
    if axis is not None and _in_named_trace(axis):
        out = _apply(tensor, lambda v: lax.all_gather(v, axis))
        n = out.shape[0]
        if gather_list is not None:
            gather_list.clear()
            for i in range(n):
                gather_list.append(out[i])
        return _Task(gather_list if gather_list is not None else out)
    if gather_list is not None:
        gather_list.clear()
        gather_list.append(tensor)
    return _Task(gather_list)


class P2POp:
    """reference: paddle.distributed.P2POp — one op of a batched P2P
    exchange.  op: distributed.isend / distributed.irecv."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def isend(tensor, dst=0, group=None):
    raise RuntimeError(
        "point-to-point isend is not exposed eagerly on TPU; batch the "
        "exchange with distributed.batch_isend_irecv (lowered to ONE "
        "lax.ppermute inside shard_map) or use p2p.ppermute directly")


def irecv(tensor, src=0, group=None):
    raise RuntimeError(
        "point-to-point irecv is not exposed eagerly on TPU; batch the "
        "exchange with distributed.batch_isend_irecv (lowered to ONE "
        "lax.ppermute inside shard_map) or use p2p.ppermute directly")


def batch_isend_irecv(p2p_op_list):
    """reference: paddle.distributed.batch_isend_irecv.  TPU-native: the
    whole batch must describe a permutation (each rank sends one tensor,
    receives one) and lowers to a single ``lax.ppermute`` — XLA's native
    neighbor exchange over ICI (this is exactly how the pipeline runtime
    rotates activations).  Must run inside shard_map on the group axis;
    the isend op's tensor supplies the payload, the matching irecv's
    tensor is rebound to the received value."""
    sends = [p for p in p2p_op_list if p.op is isend]
    recvs = [p for p in p2p_op_list if p.op is irecv]
    if not sends or len(sends) != len(recvs):
        raise ValueError(
            "batch_isend_irecv needs a balanced send/recv batch "
            f"(got {len(sends)} sends, {len(recvs)} recvs)")
    group = sends[0].group
    axis = _axis_of(group)
    if axis is None or not _in_named_trace(axis):
        raise RuntimeError(
            "batch_isend_irecv must run inside shard_map over the group "
            "axis (TPU p2p is the ppermute collective)")
    from .env import get_world_size
    n = group.nranks if group is not None and hasattr(group, "nranks") \
        else get_world_size()
    from .env import get_rank
    rank = get_rank()
    tasks = []
    # single-program SPMD: each send's declared peer implies a uniform
    # shift (every rank sends to rank+shift), which IS a permutation.
    # The inference is only sound if a recv in the batch declares the
    # matching source (rank-shift) — pair by shift, not declaration
    # order (the reference imposes no send/recv ordering).  A
    # rank-dependent pattern (e.g. pairwise even/odd exchange) has no
    # matching recv and is rejected loudly instead of silently tracing
    # the wrong permutation on every rank but this one.
    unmatched = list(recvs)
    for s in sends:
        shift = (s.peer - rank) % n
        r = next((x for x in unmatched if (rank - x.peer) % n == shift),
                 None)
        if r is None:
            raise ValueError(
                f"batch_isend_irecv: this rank sends to rank+{shift} but "
                "no irecv in the batch declares the matching source "
                f"rank-{shift}; the SPMD lowering bakes ONE uniform "
                "shift per send/recv pair into the traced program, so "
                "peers must describe the same rotation on every rank. "
                "For a non-rotation permutation build the static perm "
                "list yourself with p2p.ppermute")
        unmatched.remove(r)
        perm = [(i, (i + shift) % n) for i in range(n)]
        out = _apply(s.tensor, lambda v, _p=perm: lax.ppermute(v, axis, _p))
        r.tensor._value = out._value
        r.tensor._node = out._node
        r.tensor._out_idx = out._out_idx
        tasks.append(_Task(r.tensor))
    if unmatched:
        raise ValueError(
            f"batch_isend_irecv: {len(unmatched)} irecv(s) matched no "
            "isend shift (peers "
            f"{[x.peer for x in unmatched]}); every recv must pair with "
            "a send describing the same rotation, or its buffer would "
            "silently keep stale data")
    return tasks


def get_backend(group=None):
    """reference: paddle.distributed.get_backend — this framework's
    collectives are XLA's (ICI/DCN), reported as 'XLA'."""
    return "XLA"
