"""paddle.distributed.communication (reference:
python/paddle/distributed/communication/ — the package the collective
API migrated to; paddle.distributed re-exports it).

Here the implementations live in ``distributed.collective`` (XLA
collectives over ICI/DCN); this package provides the reference import
paths, including the ``stream`` namespace (on TPU there are no CUDA
streams — PJRT owns scheduling — so stream.* are the same ops; the
sync_op/use_calc_stream flags are accepted and meaningless)."""
from ..collective import (  # noqa: F401
    all_reduce, all_gather, reduce, broadcast, scatter, reduce_scatter,
    alltoall, alltoall_single, send, recv, barrier, ReduceOp,
    all_gather_object, broadcast_object_list, scatter_object_list,
    gather, batch_isend_irecv, P2POp, isend, irecv, get_backend)
from . import stream  # noqa: F401
