"""paddle.distributed.communication.stream — stream-variant collective
API (reference: .../communication/stream/).  PJRT owns scheduling on
TPU; these are the same XLA collectives (flags accepted, no-op)."""
from ..collective import (  # noqa: F401
    all_reduce, all_gather, reduce, broadcast, scatter, reduce_scatter,
    alltoall, alltoall_single, send, recv)
