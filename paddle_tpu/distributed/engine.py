"""Placement engine: DistributedStrategy → GSPMD shardings.

The reference implements each parallelism as a separate runtime protocol
(C++ Reducer for DP, GroupSharded hooks for ZeRO, program rewrites for
static graph: paddle/fluid/imperative/reducer.cc,
fleet/meta_parallel/sharding/*).  TPU-native, every one of them is a
*placement* of the same compiled train step over a named mesh:

- DP        → batch sharded on the "data" axis; params replicated; XLA
              inserts the gradient psum (this is the Reducer, for free).
- ZeRO-1/2  → optimizer state (and with os_g the grad reduce) sharded on
              the "sharding" axis: moments get a NamedSharding along that
              axis, so XLA reduce-scatters grads into the update and
              all-gathers fresh params — exactly GroupShardedStage2's
              wire pattern, chosen by the SPMD partitioner.
- ZeRO-3    → parameters themselves sharded on "sharding"; XLA all-gathers
              per use site (= stage-3 re-gather on forward/backward).
- TP        → layers annotate weights with a ``pspec`` (mp_layers set
              e.g. ("model", None)); activations follow by propagation.
- sep (M5)  → sequence dim of activations sharded; attention reshards
              head↔seq with all_to_all inside the layer.

One PlacementPlan holds the mesh + the rules; the hapi stepper consumes it
to device_put state and to set in/out shardings on the jitted step.
"""
import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["PlacementPlan", "make_data_parallel_plan", "plan_from_hcg"]


def _divisible_dim(shape, k, prefer_largest=True):
    """First/largest dim index divisible by k, else None."""
    cands = [i for i, s in enumerate(shape) if s % k == 0 and s >= k]
    if not cands:
        return None
    if prefer_largest:
        return max(cands, key=lambda i: shape[i])
    return cands[0]


class PlacementPlan:
    """Mesh + placement rules for params / optimizer state / batch."""

    def __init__(self, mesh, batch_axes=("data", "sharding"),
                 level=None, fsdp_axis="sharding", mp_axis="model",
                 sep_axis="sep", grad_comm=None):
        self.mesh = mesh
        # GradCommConfig for the explicit bucketed/quantized reducer
        # (hapi stepper's shard_map path); None = GSPMD inserts the
        # gradient all-reduce as before
        self.grad_comm = grad_comm if grad_comm is not None and \
            getattr(grad_comm, "enabled", False) else None
        self.batch_axes = tuple(a for a in batch_axes
                                if a in mesh.axis_names and
                                mesh.shape[a] > 1) or None
        self.level = level          # None | 'os' | 'os_g' | 'p_g_os'
        self.fsdp_axis = fsdp_axis if fsdp_axis in mesh.axis_names else None
        self.mp_axis = mp_axis if mp_axis in mesh.axis_names else None
        self.sep_axis = sep_axis if sep_axis in mesh.axis_names else None

    # -- specs ---------------------------------------------------------------
    @property
    def fsdp_size(self):
        return self.mesh.shape[self.fsdp_axis] if self.fsdp_axis else 1

    def param_pspec(self, tensor_or_shape, name=None, pspec=None):
        """PartitionSpec for a parameter.

        Priority: explicit ``pspec`` attribute (TP layers / shard_tensor)
        > ZeRO-3 sharding on the fsdp axis > replicated.
        """
        explicit = pspec if pspec is not None else \
            getattr(tensor_or_shape, "pspec", None)
        if explicit is not None:
            return P(*explicit)
        shape = tensor_or_shape if isinstance(tensor_or_shape, (tuple, list)) \
            else tuple(tensor_or_shape.shape)
        if self.level == "p_g_os" and self.fsdp_size > 1:
            dim = _divisible_dim(shape, self.fsdp_size)
            if dim is not None:
                spec = [None] * len(shape)
                spec[dim] = self.fsdp_axis
                return P(*spec)
        return P()

    def opt_pspec(self, param_spec, shape):
        """Spec for a param-shaped optimizer moment.  ZeRO-1/2/3: ensure it
        is sharded on the fsdp axis (stage-3 moments inherit the param's
        sharding, which already contains it)."""
        if self.level in ("os", "os_g", "p_g_os") and self.fsdp_size > 1:
            if self.fsdp_axis not in (param_spec or ()):
                dim = _divisible_dim(shape, self.fsdp_size)
                if dim is not None:
                    spec = list(param_spec) + \
                        [None] * (len(shape) - len(param_spec))
                    if spec[dim] is None:
                        spec[dim] = self.fsdp_axis
                        return P(*spec)
        return param_spec

    def input_pspec(self, ndim, batch_dim=0):
        if not self.batch_axes or ndim == 0:
            return P()
        spec = [None] * ndim
        spec[batch_dim] = self.batch_axes if len(self.batch_axes) > 1 \
            else self.batch_axes[0]
        return P(*spec)

    # -- shardings -----------------------------------------------------------
    def sharding(self, pspec):
        return NamedSharding(self.mesh, pspec)

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def param_sharding(self, tensor, name=None):
        return self.sharding(self.param_pspec(tensor, name))

    def input_sharding(self, ndim, batch_dim=0):
        return self.sharding(self.input_pspec(ndim, batch_dim))

    def opt_state_shardings(self, opt_state, param_specs, param_shapes):
        """Map the optimizer state pytree (list-per-param of {name: arr})
        to shardings: param-shaped leaves get opt_pspec, scalars
        replicated."""
        out = []
        for st, pspec, shape in zip(opt_state, param_specs, param_shapes):
            mapped = {}
            for k, v in st.items():
                if tuple(np.shape(v)) == tuple(shape):
                    mapped[k] = self.sharding(self.opt_pspec(pspec, shape))
                else:
                    mapped[k] = self.replicated()
            out.append(mapped)
        return out

    def describe(self):
        return (f"PlacementPlan(mesh={dict(self.mesh.shape)}, "
                f"batch_axes={self.batch_axes}, level={self.level})")


def make_data_parallel_plan(devices=None, level=None, grad_comm=None):
    """All visible devices on one 'data' axis (optionally ZeRO 'sharding'
    semantics on the same axis — reference: pure-DP GroupSharded uses the
    world group).  ``grad_comm.zero1`` is the strategy-flag spelling of
    ``level="os"``: shard the weight update across the replicas
    themselves (PAPERS.md "Automatic Cross-Replica Sharding of Weight
    Update in Data-Parallel Training")."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if grad_comm is not None and grad_comm.zero1 and level is None:
        level = "os"
    if level in ("os", "os_g", "p_g_os"):
        mesh = Mesh(devs.reshape(1, -1), ("data", "sharding"))
    else:
        mesh = Mesh(devs, ("data",))
    return PlacementPlan(mesh, level=level, grad_comm=grad_comm)


def plan_from_hcg(hcg, level=None, grad_comm=None):
    """Build the plan from a HybridCommunicateGroup (fleet.init output).

    With ``grad_comm.zero1`` on a topology whose dedicated sharding axis
    is degenerate (sharding_degree == 1), the *data* axis becomes the
    fsdp axis: the optimizer state shards across replicas and GSPMD
    emits the reduce-scatter-into-update + all-gather wire pattern."""
    fsdp_axis = "sharding"
    if grad_comm is not None and grad_comm.zero1:
        if level is None:
            level = "os"
        shape = dict(hcg.jax_mesh.shape)
        if shape.get("sharding", 1) <= 1 and shape.get("data", 1) > 1:
            fsdp_axis = "data"
    return PlacementPlan(hcg.jax_mesh, level=level, fsdp_axis=fsdp_axis,
                         grad_comm=grad_comm)
