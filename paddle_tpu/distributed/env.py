"""Parallel environment bootstrap (reference:
python/paddle/distributed/parallel.py — init_parallel_env/ParallelEnv;
the TCPStore+NCCL rendezvous becomes ``jax.distributed.initialize``).

Two regimes:
- single-process multi-device (one host driving N TPU chips, or N forced
  CPU devices in tests): world is jax.device_count(), no rendezvous needed.
- multi-process/multi-host: PADDLE_* env (set by the launcher) maps onto
  jax.distributed.initialize(coordinator, num_processes, process_id).
"""
import os

import jax

_STATE = {"initialized": False, "mesh": None}


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ParallelEnv:
    def __init__(self):
        self.rank = _env_int("PADDLE_TRAINER_ID", 0)
        self.world_size = _env_int("PADDLE_TRAINERS_NUM", 1)
        self.device_id = _env_int("FLAGS_selected_tpus",
                                  _env_int("FLAGS_selected_gpus", 0))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = eps.split(",") if eps else []
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.device_id


def init_parallel_env():
    """Bootstrap multi-process JAX if PADDLE_* env says so; no-op extra
    calls.  Returns a ParallelEnv."""
    env = ParallelEnv()
    if _STATE["initialized"]:
        return env
    nproc = _env_int("PADDLE_TRAINERS_NUM", 1)
    if nproc > 1 and os.environ.get("PADDLE_MASTER"):
        coordinator = os.environ["PADDLE_MASTER"]
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=nproc,
            process_id=env.rank)
    _STATE["initialized"] = True
    return env


def is_initialized():
    return _STATE["initialized"]


def is_available():
    """reference: paddle.distributed.is_available — collectives are
    always compiled in (XLA ships them); True unconditionally."""
    return True


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index()) \
            if hasattr(group, "get_group_rank") else jax.process_index()
    return _env_int("PADDLE_TRAINER_ID", jax.process_index())


def get_world_size(group=None):
    if group is not None and hasattr(group, "world_size"):
        return group.world_size
    n = _env_int("PADDLE_TRAINERS_NUM", 0)
    return n if n > 0 else jax.process_count()


def parallel_device_count():
    """Devices visible to this process (the SPMD width for shard_map)."""
    return jax.local_device_count()
