"""Fleet facade (reference: python/paddle/distributed/fleet/).

M2/M4 fill the full hybrid-parallel stack; the facade object and
DistributedStrategy live here.
"""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import (CommunicateTopology,  # noqa: F401
                            HybridCommunicateGroup)
from .fleet import (Fleet, init, distributed_model,  # noqa: F401
                    distributed_optimizer, get_hybrid_communicate_group,
                    worker_num, worker_index, is_first_worker,
                    barrier_worker, save_persistables, stop_worker,
                    register_ps_client, is_worker, is_server, server_num,
                    server_index, server_endpoints, worker_endpoints,
                    init_worker, init_server, run_server,
                    save_inference_model, UtilBase, util)
from .base.role_maker import (PaddleCloudRoleMaker,  # noqa: F401
                              UserDefinedRoleMaker, Role)
from . import utils  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import elastic  # noqa: F401
