"""DistributedStrategy (reference: paddle/fluid/framework/
distributed_strategy.proto + python/paddle/distributed/fleet/base/
distributed_strategy.py — a protobuf-backed ~60-field strategy object).

TPU-native: a plain dataclass-style config tree, serializable to dict/json.
``fleet.distributed_model`` compiles it into a Mesh + sharding rules.
"""
import copy
import json

__all__ = ["DistributedStrategy"]

_DEFAULT_HYBRID = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                   "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1}


class DistributedStrategy:
    def __init__(self):
        # mirrors the reference's field set (subset that is meaningful on TPU)
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16":
                            False, "use_fp16_guard": False,
                            "custom_white_list": [], "custom_black_list": [],
                            "dtype": "bfloat16", "level": "O1"}
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "sharding_degree": 1,
                                 "segment_broadcast_MB": 32,
                                 "offload": False}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1,
                                        "tensor_init_seed": -1}
        self.hybrid_configs = dict(_DEFAULT_HYBRID)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lamb_configs = {}
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.localsgd = False
        self.a_sync = False
        self.a_sync_configs = {}
        self.heter_ccl_mode = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1  # accepted, unused (XLA owns comms)
        self.sync_nccl_allreduce = False
        self.fp16_allreduce = False
        self.without_graph_optimization = False
        self.asp = False
        self.qat = False
        self.qat_configs = {}
        # communication-efficient gradient reduction (distributed/
        # grad_comm.py): bucketed backward-overlapped all-reduce in the
        # compiled DP step, opt-in quantized wire format, and ZeRO-1
        # cross-replica sharding of the weight update as a flag.  Keys
        # mirror GradCommConfig; bucket_mb=None defaults to
        # fuse_grad_size_in_MB (the reference's fuse knob).
        self.grad_comm = False
        self.grad_comm_configs = {"bucket_mb": None, "overlap": True,
                                  "quantize": None, "quant_chunk": 65536,
                                  "zero1": False}
        # training guardian (framework/guardian.py): numeric sentinel +
        # skip-and-rollback ladder + collective watchdog.  Keys mirror
        # GuardianConfig's constructor; Model.fit picks this up via
        # GuardianConfig.from_strategy when fleet.init ran with it on.
        self.guardian = False
        self.guardian_configs = {"check_grads": True, "loss_spike": True,
                                 "spike_zscore": 6.0, "spike_warmup": 20,
                                 "skip_limit": 3, "skip_window": 2,
                                 "max_rollbacks": 2, "ckpt_every": 50,
                                 "ckpt_root": None}

    def to_dict(self):
        return {k: copy.deepcopy(v) for k, v in self.__dict__.items()}

    def from_dict(self, d):
        for k, v in d.items():
            setattr(self, k, copy.deepcopy(v))
        return self

    def save_to_prototxt(self, output):
        with open(output, "w") as f:
            json.dump(self.to_dict(), f, indent=2)

    def load_from_prototxt(self, pb_file):
        with open(pb_file) as f:
            self.from_dict(json.load(f))

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return (f"DistributedStrategy(enabled={on}, "
                f"hybrid={self.hybrid_configs})")
