"""Role makers (reference: python/paddle/distributed/fleet/base/
role_maker.py — PaddleCloudRoleMaker parses the launcher env;
UserDefinedRoleMaker takes explicit placement).

The collective path needs only rank/world (jax.distributed owns the
actual bootstrap); the PS path carries worker/server roles + endpoint
lists for the socket parameter server (distributed/ps).
"""
import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id if self.is_worker() else -1

    def server_index(self):
        return self._current_id if self.is_server() else -1

    def worker_num(self):
        return self._worker_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parse the launcher environment (reference env contract:
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / TRAINING_ROLE /
    PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_TRAINER_ENDPOINTS /
    POD_IP + PADDLE_PORT)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e]
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        if role == "PSERVER":
            self._role = Role.SERVER
            ip = os.environ.get("POD_IP", "127.0.0.1")
            port = os.environ.get("PADDLE_PORT", "0")
            me = f"{ip}:{port}"
            if self._server_endpoints and me not in self._server_endpoints:
                raise ValueError(
                    f"PSERVER endpoint {me!r} (POD_IP:PADDLE_PORT) is not "
                    f"in PADDLE_PSERVERS_IP_PORT_LIST "
                    f"{self._server_endpoints} — misconfigured env")
            self._current_id = (self._server_endpoints.index(me)
                                if me in self._server_endpoints else 0)
        else:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit placement (reference: UserDefinedRoleMaker kwargs:
    current_id, role, worker_num, server_endpoints)."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__()
        self._current_id = int(kwargs.get("current_id", 0))
        self._role = kwargs.get("role", Role.WORKER)
        self._worker_num = int(kwargs.get("worker_num", 1))
        self._server_endpoints = list(kwargs.get("server_endpoints", []))
        self._worker_endpoints = list(kwargs.get("worker_endpoints", []))
