"""Elastic training (reference:
python/paddle/distributed/fleet/elastic/manager.py — ETCD-based node
membership with lease+heartbeat, scale-in/out watch, relaunch with new
ranks within an ``--np min:max`` range).

TPU-native: the membership registry is the framework's own TCPStore (the
same rendezvous store used for comm bootstrap) instead of an external ETCD
cluster; semantics are identical — register with a heartbeat lease, watch
the member set, and report RESTART/HOLD/NORMAL to the launcher, which
tears down workers and relaunches with recomputed
``PADDLE_TRAINER_ENDPOINTS``.  Multi-host TPU jobs pair this with fast
sharded-checkpoint resume (SURVEY §5.3).
"""
import json
import os
import threading
import time

from ....framework import failpoints as _fp
from ...store import TCPStore

__all__ = ["ElasticStatus", "ElasticLevel", "ElasticManager"]

_FP_HEARTBEAT = _fp.register("elastic.heartbeat")
# fired by the launcher when a membership change relaunches workers at
# the observed member count with resume pointed at the manifest root —
# `elastic.reshard=error` makes the relaunch-with-resume path itself
# chaos-testable (delay:S parks it mid-reshard)
FP_RESHARD = _fp.register("elastic.reshard")


class _NpWaitResult(int):
    """Result of :meth:`ElasticManager.wait_for_np`: the observed member
    count, truthy only when the count reached quorum — so
    ``if not mgr.wait_for_np():`` keeps working while error messages can
    say how many nodes actually showed up."""

    def __new__(cls, count, ok):
        obj = super().__new__(cls, count)
        obj.ok = ok
        return obj

    def __bool__(self):
        return self.ok


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"          # below min nodes: wait
    RESTART = "restart"    # membership changed: relaunch with new ranks
    NORMAL = "normal"
    EXIT = "exit"


class ElasticLevel:
    NONE = 0
    FAULT_TOLERANCE = 1    # fixed np, survive restarts
    ELASTIC = 2            # np range, scale in/out


class ElasticManager:
    """Store-backed membership manager.

    Parameters mirror the reference manager: ``np`` is "N" or "min:max",
    ``host``/``curr_port`` identify this node, ``scale``/``force`` knobs
    kept for CLI compat.
    """

    _PREFIX = "elastic"

    def __init__(self, np="1", host=None, store=None, master=None,
                 heartbeat_interval=2.0, elastic_timeout=30.0,
                 job_id="default"):
        np = str(np)
        if ":" in np:
            lo, hi = np.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(np)
        self.elastic_level = (ElasticLevel.ELASTIC
                              if self.max_np > self.min_np
                              else ElasticLevel.FAULT_TOLERANCE)
        self.host = host or os.environ.get("POD_IP", "127.0.0.1")
        self.job_id = job_id
        self.heartbeat_interval = heartbeat_interval
        self.elastic_timeout = elastic_timeout
        if store is not None:
            self._store = store
        else:
            master = master or os.environ.get("PADDLE_MASTER",
                                              "127.0.0.1:6768")
            h, p = master.rsplit(":", 1)
            self._store = TCPStore(h, int(p), is_master=False)
        self._node_id = None
        self._hb_thread = None
        self._stopped = threading.Event()
        self._last_members = None
        self._last_full_round = 0.0   # when a complete probe round ran
        self._store_lost = False      # cache expired with store still down
        # ids with no readable record get backoff deadlines instead of a
        # permanent blacklist: transient store slowness must not evict a
        # live peer (they are re-probed after the backoff lapses)
        self._dead_until = {}
        self._miss_counts = {}
        self.enabled = self.elastic_level != ElasticLevel.NONE

    # -- keys ---------------------------------------------------------------
    def _k(self, *parts):
        return "/".join((self._PREFIX, self.job_id) + parts)

    # -- lifecycle ----------------------------------------------------------
    def start(self, endpoint=None):
        """Register this node and start the heartbeat lease."""
        self._node_id = self._store.add(self._k("seq"), 1) - 1
        self._endpoint = endpoint or f"{self.host}:0"
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self._node_id

    def _beat(self):
        if _fp._ACTIVE:
            _fp.fire(_FP_HEARTBEAT)
        rec = {"endpoint": self._endpoint, "ts": time.time(), "alive": True}
        # short retry budget: a stale beat is worthless, and a beat
        # parked in the client's full resilience envelope would pin the
        # loop; fail fast, the next interval retries
        self._store.set(self._k("node", str(self._node_id)),
                        json.dumps(rec).encode(),
                        retry_budget=max(self.heartbeat_interval, 2.0))

    def _hb_loop(self):
        # the lease loop NEVER gives up on store trouble: during an
        # outage peers evict this node by lease expiry anyway, and the
        # first beat after the store returns re-registers the record —
        # rejoin is exactly the elastic behavior wanted.  Each failed
        # beat is bounded by _beat's short retry budget.
        while not self._stopped.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                pass
        # stop() raced an in-flight beat that may have been parked in the
        # store client's retry envelope longer than stop()'s bounded
        # join: re-write the tombstone on the way out so the last word
        # in the store is always "dead", never a stale "alive" beat
        self._write_tombstone()

    def _write_tombstone(self):
        if self._node_id is None:
            return

        # best-effort parting word of a dying node: it must never stall
        # the launcher's SIGTERM grace.  retry_budget bounds the Python
        # client; the native client ignores it (its C API has no budget
        # knob), so the write also runs on a daemon thread with a
        # bounded join — wall time is capped for both client types.
        def _do():
            try:
                rec = {"endpoint": self._endpoint, "ts": 0,
                       "alive": False}
                self._store.set(self._k("node", str(self._node_id)),
                                json.dumps(rec).encode(),
                                retry_budget=2.0)
            except Exception:
                pass
        t = threading.Thread(target=_do, daemon=True)
        t.start()
        t.join(timeout=3.0)

    def stop(self):
        self._stopped.set()
        # join the heartbeat thread (bounded) BEFORE writing the
        # tombstone: an in-flight beat racing the tombstone could
        # re-mark this dying node "alive" and stall the peers' RESTART
        # detection for a full lease window.  If the join times out
        # (beat parked in store retry), _hb_loop re-writes the tombstone
        # itself when that beat finally returns.
        t = self._hb_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.heartbeat_interval * 2 + 1.0)
        self._write_tombstone()

    def exit(self, completed=True):
        self.stop()
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR

    # -- membership ---------------------------------------------------------
    def _members(self):
        """Fresh member records {node_id: endpoint} (heartbeat within the
        lease window), capped at max_np (lowest ids win, matching the
        reference's membership cap).  This node is always included from
        local knowledge, so a transient store hiccup can never hand our
        rank to someone else.  Ids that repeatedly have no record (died
        between registration and first heartbeat) are remembered as dead
        and skipped, keeping watch() latency flat."""
        truncated = False
        try:
            seq = self._store.add(self._k("seq"), 0)
        except Exception:
            # store unreachable before a single probe ran: this round is
            # as incomplete as one truncated mid-probe — fall through to
            # the last-known-good fallback, not an empty membership
            seq = 0
            truncated = True
        now = time.time()
        lease = max(self.heartbeat_interval * 3, 6.0)
        members = {}
        for nid in range(seq):
            if self._stopped.is_set():
                truncated = True
                break              # stop() mid-round: bail out promptly
            if self._dead_until.get(nid, 0) > now:
                continue
            try:
                raw = self._store.get(self._k("node", str(nid)),
                                      timeout=1.0)
            except KeyError:       # store healthy, record absent: a miss
                self._miss_counts[nid] = self._miss_counts.get(nid, 0) + 1
                if self._miss_counts[nid] >= 3:
                    self._dead_until[nid] = now + 10 * lease
                continue
            except Exception:
                # store-level trouble (connect/retry budget burned): one
                # failed probe already cost a full client retry envelope,
                # so probing the remaining ids would stack envelopes and
                # make this round — and wait_for_np's timeout — minutes
                # long.  Abort the round; nobody gets a miss charged for
                # store downtime.
                truncated = True
                break
            self._miss_counts.pop(nid, None)
            self._dead_until.pop(nid, None)
            try:
                rec = json.loads(raw.decode())
            except Exception:
                continue
            if rec.get("alive") and now - rec["ts"] <= lease:
                members[nid] = rec["endpoint"]
        self._store_lost = False
        if truncated and self._last_members and \
                now - self._last_full_round <= 3 * lease:
            # an incomplete probe round must not masquerade as a
            # membership CHANGE — watch() would force a spurious full
            # relaunch over a transient store fault.  Report the last
            # complete round instead (this node re-added from local
            # knowledge, as below).  Bounded: once the cache outlives
            # three lease windows the store is not "flapping", it is
            # gone — watch() then reports HOLD (see _store_lost) so the
            # launcher's hold-timeout give-up path engages.
            print("[elastic] store unreachable; serving last-known "
                  "membership", flush=True)
            members = dict(self._last_members)
        elif truncated and self._last_members:
            print("[elastic] store unreachable beyond the lease window; "
                  "last-known membership expired", flush=True)
            self._store_lost = True
        elif not truncated:
            self._last_full_round = now
        if self._node_id is not None and not self._stopped.is_set():
            members.setdefault(self._node_id, getattr(self, "_endpoint",
                                                      f"{self.host}:0"))
        if len(members) > self.max_np:
            keep = sorted(members)[:self.max_np]
            members = {k: members[k] for k in keep}
        return members

    def endpoints(self):
        """Ordered endpoint list of the current membership (rank order =
        node-id order, the reference's sorted-hosts rule)."""
        m = self._members()
        return [m[k] for k in sorted(m)]

    def watch(self):
        """One membership poll → status for the launcher loop."""
        members = self._members()
        if self._store_lost:
            # the registry is gone, not flapping: with no control plane
            # there is nothing trustworthy to RESTART onto — a partial
            # view here could relaunch every node as its own singleton
            # job (split brain).  HOLD until the store returns or the
            # launcher's hold timeout gives up.
            return ElasticStatus.HOLD
        n = len(members)
        if self._last_members is None:
            self._last_members = members
        if n < self.min_np:
            return ElasticStatus.HOLD
        if members != self._last_members:
            self._last_members = members
            return ElasticStatus.RESTART
        return ElasticStatus.NORMAL

    def wait_for_np(self, timeout=None):
        """Block until member count is within [min_np, max_np].

        Returns an int-like result: the observed member count, truthy
        only when quorum was reached — callers can both test success and
        report how many nodes actually showed up.  Polls on the stop
        event (not a bare sleep) so :meth:`stop` interrupts the wait
        promptly."""
        timeout = timeout if timeout is not None else self.elastic_timeout
        deadline = time.time() + timeout
        while True:
            n = len(self._members())
            if self.min_np <= n <= self.max_np:
                return _NpWaitResult(n, True)
            if time.time() >= deadline or self._stopped.is_set():
                return _NpWaitResult(n, False)
            if self._stopped.wait(min(self.heartbeat_interval / 2,
                                      max(0.0, deadline - time.time()))):
                return _NpWaitResult(n, False)
