"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py).

``fleet.init(strategy)`` builds the hybrid topology;
``distributed_model``/``distributed_optimizer`` wrap by parallel mode —
here they compile the DistributedStrategy into mesh-axis sharding rules
(M2/M4 wire DP/sharding/TP/PP wrappers in meta_parallel/).
"""
import numpy as np
import jax

from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from ..env import init_parallel_env, get_rank, get_world_size

_FLEET = {"strategy": None, "hcg": None, "initialized": False}


class UtilBase:
    """reference: fleet/base/util_factory.py UtilBase — small worker-group
    utilities exposed as fleet.util."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..collective import all_reduce as _ar, ReduceOp
        import numpy as _np
        from ...framework.core import Tensor
        import jax.numpy as _jnp
        t = input if isinstance(input, Tensor) else \
            Tensor(_jnp.asarray(_np.asarray(input)))
        op = {"sum": ReduceOp.SUM, "min": ReduceOp.MIN,
              "max": ReduceOp.MAX}[mode]
        _ar(t, op=op)
        return _np.asarray(t._value)

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _b
        _b()

    def all_gather(self, input, comm_world="worker"):
        from ..collective import all_gather_object
        out = []
        all_gather_object(out, input)
        return out

    def get_file_shard(self, files):
        """Split a file list contiguously across workers (reference
        semantics: len%n remainder spread over the first ranks).  Uses
        the registered role maker's placement when one exists (PS mode);
        collective rank/world otherwise."""
        rm = _FLEET.get("role_maker")
        if rm is not None:
            n = max(rm.worker_num(), 1)
            rank = max(rm.worker_index(), 0)
        else:
            n = max(get_world_size(), 1)
            rank = max(get_rank(), 0)
        total = len(files)
        base, rem = divmod(total, n)
        start = rank * base + min(rank, rem)
        return list(files[start:start + base + (1 if rank < rem else 0)])

    def print_on_rank(self, message, rank_id=0):
        if get_rank() == rank_id:
            print(message)


class Fleet:
    def __init__(self):
        pass

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        if strategy is None:
            strategy = DistributedStrategy()
        _FLEET["strategy"] = strategy
        if role_maker is None and not is_collective:
            from .base.role_maker import PaddleCloudRoleMaker
            role_maker = PaddleCloudRoleMaker(is_collective=False)
        _FLEET["role_maker"] = role_maker
        if role_maker is not None and role_maker.is_server():
            # PS server process: no collective mesh to build
            _FLEET["initialized"] = True
            return self
        init_parallel_env()
        h = strategy.hybrid_configs
        n_dev = jax.device_count()
        degrees = {"data": h.get("dp_degree", 1),
                   "pipe": h.get("pp_degree", 1),
                   "sharding": h.get("sharding_degree", 1),
                   "sep": h.get("sep_degree", 1),
                   "model": h.get("mp_degree", 1)}
        specified = int(np.prod(list(degrees.values())))
        if degrees["data"] == 1 and specified < n_dev and \
                n_dev % max(specified, 1) == 0:
            # reference behavior: dp fills the remainder
            degrees["data"] = n_dev // specified
        topo = CommunicateTopology(list(degrees.keys()),
                                   list(degrees.values()))
        _FLEET["hcg"] = HybridCommunicateGroup(topo)
        _FLEET["initialized"] = True
        return self

    @property
    def is_initialized(self):
        return _FLEET["initialized"]

    def distributed_model(self, model):
        from .meta_parallel import wrap_distributed_model
        wrapped = wrap_distributed_model(model, _FLEET["strategy"],
                                         _FLEET["hcg"])
        _FLEET["model"] = wrapped
        return wrapped

    def distributed_optimizer(self, optimizer, strategy=None):
        from .meta_parallel import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer,
                                       _FLEET["hcg"],
                                       strategy or _FLEET["strategy"])

    def _rm(self):
        return _FLEET.get("role_maker")

    def worker_num(self):
        rm = self._rm()
        return rm.worker_num() if rm is not None else get_world_size()

    def worker_index(self):
        rm = self._rm()
        return rm.worker_index() if rm is not None else get_rank()

    def is_first_worker(self):
        rm = self._rm()
        return rm.is_first_worker() if rm is not None else get_rank() == 0

    def is_worker(self):
        rm = self._rm()
        return rm.is_worker() if rm is not None else True

    def is_server(self):
        rm = self._rm()
        return rm.is_server() if rm is not None else False

    def server_num(self):
        rm = self._rm()
        return rm.server_num() if rm is not None else 0

    def server_index(self):
        rm = self._rm()
        return rm.server_index() if rm is not None else -1

    def server_endpoints(self, to_string=False):
        rm = self._rm()
        eps = rm.get_pserver_endpoints() if rm is not None else []
        return ",".join(eps) if to_string else eps

    def worker_endpoints(self, to_string=False):
        rm = self._rm()
        eps = rm.get_trainer_endpoints() if rm is not None else []
        return ",".join(eps) if to_string else eps

    def init_worker(self, scopes=None):
        """PS mode: connect this worker to the parameter servers
        (reference: fleet.init_worker starts the brpc client)."""
        eps = self.server_endpoints()
        if not eps:
            return           # collective mode: nothing to connect
        from ..ps import PSClient
        client = PSClient(eps)
        _FLEET["ps_client"] = client
        return client

    def init_server(self, *args, **kwargs):
        """PS mode: create this process's parameter-server shard
        (reference: fleet.init_server loads tables before run)."""
        from ..ps import PSServer
        rm = self._rm()
        host, port = "127.0.0.1", 0
        if rm is not None and rm.server_index() >= 0 and \
                rm.get_pserver_endpoints():
            me = rm.get_pserver_endpoints()[rm.server_index()]
            host, _, port_s = me.rpartition(":")
            host, port = host or "127.0.0.1", int(port_s)
        server = PSServer(port=port, host=host)
        _FLEET["ps_server"] = server
        return server

    def run_server(self):
        """PS mode: serve until stopped (reference: fleet.run_server
        blocks in the brpc service loop).  PSServer already serves from
        a daemon thread; block on it."""
        server = _FLEET.get("ps_server")
        if server is None:
            if not self.server_endpoints():
                raise RuntimeError(
                    "run_server: no parameter-server endpoints configured "
                    "(fleet.init with a PS role maker first) — refusing to "
                    "serve an undiscoverable ephemeral port")
            server = self.init_server()
        server._thread.join()

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def get_hybrid_communicate_group(self):
        return _FLEET["hcg"]

    @property
    def strategy(self):
        return _FLEET["strategy"]

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        """Save trainable state (reference: fleet.save_persistables —
        PS mode saves the server tables, collective mode the program
        persistables).  Here: a registered PS client saves its tables;
        otherwise the last distributed_model's state_dict is written as
        a sharded distributed checkpoint."""
        if dirname is None:
            raise ValueError("save_persistables needs dirname")
        client = _FLEET.get("ps_client")
        if client is not None:
            client.save_persistables(dirname)
            return
        model = _FLEET.get("model")
        if model is None:
            raise RuntimeError(
                "save_persistables: no PS client registered and no model "
                "wrapped via fleet.distributed_model yet")
        from ..checkpoint import save_state_dict
        save_state_dict(model.state_dict(), dirname)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True, mode=0):
        """reference: fleet.save_inference_model — rank-0 writes the
        pruned inference program (adapter over static
        save_inference_model; the path contract keeps dirname)."""
        import os
        from ...static import (save_inference_model as _sim,
                               default_main_program)
        if not self.is_first_worker():
            return
        prog = main_program or default_main_program()
        unknown = [n for n in feeded_var_names
                   if n not in prog._placeholders]
        if unknown:
            raise KeyError(
                f"save_inference_model: feed names {unknown} are not "
                f"placeholders of the program "
                f"(have: {list(prog._placeholders)})")
        feeds = [prog._placeholders[n] for n in feeded_var_names]
        _sim(os.path.join(dirname, "model"), feeds, list(target_vars),
             executor, program=prog)

    @property
    def util(self):
        return util          # the module-level singleton (bottom of file)

    def register_ps_client(self, client):
        """Attach a distributed.ps.PSClient so save_persistables /
        stop_worker drive the parameter-server runtime."""
        _FLEET["ps_client"] = client

    def stop_worker(self):
        """Tear down PS connections (reference: fleet.stop_worker ends
        the brpc worker).  No-op in pure collective mode."""
        client = _FLEET.pop("ps_client", None)
        if client is not None:
            client.close()


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
save_persistables = fleet.save_persistables
stop_worker = fleet.stop_worker
register_ps_client = fleet.register_ps_client
is_worker = fleet.is_worker
is_server = fleet.is_server
server_num = fleet.server_num
server_index = fleet.server_index
server_endpoints = fleet.server_endpoints
worker_endpoints = fleet.worker_endpoints
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
save_inference_model = fleet.save_inference_model
util = UtilBase()
