"""Meta-parallel wrappers (reference:
python/paddle/distributed/fleet/meta_parallel/).

M2-M4 build these out (TP layers, PipelineLayer, sharding stages); the
facade-level wrap + HybridParallelOptimizer live here.
"""
from ....nn.layer.layers import Layer
from ....optimizer.optimizer import Optimizer
from .parallel_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, get_rng_state_tracker, RNGStatesTracker,
    model_parallel_random_seed)
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401


def wrap_distributed_model(model, strategy, hcg):
    """Pick the wrapper by strategy (reference: fleet.distributed_model)."""
    from ...parallel import DataParallel
    from ...grad_comm import GradCommConfig
    if hcg is None:
        return DataParallel(model, strategy=strategy)
    cc = GradCommConfig.from_strategy(strategy)
    level = None
    if strategy is not None and hcg.get_sharding_parallel_world_size() > 1:
        stage = (strategy.sharding_configs or {}).get("stage", 1)
        level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(stage, "os")
    if hcg.get_pipe_parallel_world_size() > 1:
        from .pipeline_parallel import PipelineParallel
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy, level=level,
                              grad_comm=cc)
    wrapped = DataParallel(model)
    from ...engine import plan_from_hcg
    wrapped._placement_plan = plan_from_hcg(hcg, level=level,
                                            grad_comm=cc)
    return wrapped


class TensorParallel(Layer):
    """Marker wrapper: TP layers already carry their sharding rules; this
    wrapper only pins the hcg so the engine builds the right mesh."""

    def __init__(self, layers, hcg, strategy=None, level=None,
                 grad_comm=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        from ...engine import plan_from_hcg
        self._placement_plan = plan_from_hcg(hcg, level=level,
                                             grad_comm=grad_comm)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class HybridParallelOptimizer:
    """Wraps the inner optimizer with mesh-aware global-norm clipping
    (reference: meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer
    .py).  Under GSPMD the grad allreduce is already in the compiled step;
    what remains is the cross-axis global-norm clip, which works on the
    full (replicated-view) grads transparently.  Strategy-driven
    meta-optimizers (lars/dgc swap, localsgd wrap, gradient_merge
    accumulation) are applied here, mirroring fleet's meta-optimizer
    pass."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        from ..meta_optimizers import (apply_meta_optimizers,
                                       GradientMergeHelper)
        self._inner = apply_meta_optimizers(optimizer, strategy)
        self._hcg = hcg
        self._strategy = strategy
        self._gm = None
        if strategy is not None and getattr(strategy, "gradient_merge",
                                            False):
            cfg = strategy.gradient_merge_configs or {}
            self._gm = GradientMergeHelper(cfg.get("k_steps", 1),
                                           cfg.get("avg", True))

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        if self._gm is not None:
            params = self._inner._parameter_list or []
            if self._gm.accumulate(params):
                return  # still accumulating: no apply this micro-step
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        return self._inner.minimize(loss, *a, **k)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)


class ShardingParallel(Layer):
    """reference: meta_parallel.ShardingParallel — the sharding-axis
    model wrapper.  Parameters/grads/opt-state shard via the engine's
    NamedSharding plan (GSPMD inserts the reduce_scatter/allgather the
    reference codes by hand); the wrapper is the API seam."""

    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        if hcg is not None:
            from ...engine import plan_from_hcg
            stage = 1
            if strategy is not None:
                stage = (strategy.sharding_configs or {}).get("stage", 1)
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(stage, "os")
            self._placement_plan = plan_from_hcg(hcg, level=level)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
