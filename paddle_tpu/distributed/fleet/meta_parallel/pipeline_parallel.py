"""PipelineParallel wrapper (reference: fleet/meta_parallel/
pipeline_parallel.py — train_batch with FThenB/1F1B/interleaved schedules,
micro-batch splitting, P2P meta negotiation).

TPU-native: ``train_batch`` drives ONE jitted SPMD program per batch.  Two
regimes:

- ``PipelineLayer`` with a homogeneous block run: the step compiles
  head → spmd_pipeline (shard_map + ppermute stage rotation, interleaved
  virtual stages honored) → tail → loss → grad → optimizer update.  The
  whole micro-batch schedule lives inside XLA; the only host sync is the
  final scalar loss readback.  This replaces the reference's per-rank
  1F1B send/recv runtime (SURVEY §3.4) with a compiled wavefront.
- arbitrary model: micro-batches become eager gradient accumulation
  (same math as FThenB; a wavefront adds nothing without stage-sharded
  weights).

Head/tail buffers (e.g. BN stats in a conv stem) update through the
compiled step like hapi's stepper; buffers INSIDE the homogeneous blocks
cannot ride the stacked-params rotation, so a model with block-level
buffers falls back to the eager path (checked in ``_compiled_ok``).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ....analysis import register_jit_surface
from ....nn.layer.layers import Layer
from ....framework.core import Tensor
from ....framework import autograd as _ag
from ....framework.random import rng_scope, next_key
from ...engine import plan_from_hcg
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]

# the compiled pipeline stepper body is a nested def — registered for
# the tracer-safety/donation passes (mirrored by EXTRA_JIT_SURFACES in
# paddle_tpu/analysis/allowlist.py).  Donation audit (ISSUE 11): the
# jit donates (0, 2, 3, 4) — trainable/stacked/buffer/opt-state trees
# are consumed and re-emitted; frozen params (1) stay live.
register_jit_surface(__name__, "_PipelineStepper._build.step")


def _apply_items(items, x):
    """Sequentially apply run_function entries (layer, tag) to a Tensor,
    honoring SharedLayerDesc forward_funcs and bare callables — the same
    dispatch as PipelineLayer.forward."""
    for layer, tag in items:
        if tag is not None and tag != "func" and callable(tag):
            x = tag(layer, x)
        else:
            x = layer(x)
    return x


class _PipelineStepper:
    """Compiles the full dp×tp×pp train step for a PipelineLayer.

    Parameters split into the stacked homogeneous blocks (leading layer
    dim, sharded on "pipe") and the rest (head/tail/shared — placed by
    the plan: TP pspecs, ZeRO level, replication).  The optimizer runs
    functionally inside the same executable (fused update)."""

    def __init__(self, pipe_layer, hcg, strategy, optimizer, loss_fn,
                 n_micro):
        level = None
        if strategy is not None and \
                hcg.get_sharding_parallel_world_size() > 1:
            stage = (strategy.sharding_configs or {}).get("stage", 1)
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(stage, "os")
        self.plan = plan_from_hcg(hcg, level=level)
        self.mesh = self.plan.mesh
        self.pipe_layer = pipe_layer
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.n_micro = n_micro

        start, end = pipe_layer._homogeneous_span()
        self.head = pipe_layer.run_function[:start]
        self.tail = pipe_layer.run_function[end:]
        self.staged = pipe_layer.staged_module(self.mesh, axis="pipe")
        self.blocks = self.staged.blocks
        self.t_names = [n for n, _ in
                        self.staged.template.named_parameters()]

        block_ids = {id(p) for b in self.blocks
                     for _, p in b.named_parameters()}
        named, seen = [], set()
        for n, p in pipe_layer.named_parameters():
            # shared (tied) layers appear under several prefixes — keep
            # one entry per param object so its grad contributions sum
            # into a single update
            if id(p) in block_ids or id(p) in seen:
                continue
            seen.add(id(p))
            named.append((n, p))
        self.other_params = [p for _, p in named]
        self.other_names = [n for n, _ in named]
        self.ot_idx = [i for i, p in enumerate(self.other_params)
                       if not p.stop_gradient]
        self.buffers = [b for _, b in pipe_layer.named_buffers()]

        plan = self.plan
        self._other_specs = [plan.param_pspec(p) for p in self.other_params]
        self._other_sh = [plan.sharding(s) for s in self._other_specs]
        t_params = [p for _, p in self.staged.template.named_parameters()]
        from jax.sharding import PartitionSpec as P
        self._stacked_specs = [P("pipe", *plan.param_pspec(p))
                               for p in t_params]
        self._stacked_sh = [plan.sharding(s) for s in self._stacked_specs]

        # place state
        for p, s in zip(self.other_params, self._other_sh):
            p._value = jax.device_put(p._value, s)
        self.stacked = [jax.device_put(v, s) for v, s in
                        zip(self.staged.stacked, self._stacked_sh)]
        self._buf_sh = [plan.replicated() for _ in self.buffers]
        for b, s in zip(self.buffers, self._buf_sh):
            b._value = jax.device_put(b._value, s)

        self.opt_state = None
        self._step_cache = {}
        self._dirty = False

    # -- state sync -------------------------------------------------------
    def sync_to_layers(self):
        """Write the stacked block values back into the per-block params
        (state_dict/checkpoint view).  Lazy: only after training steps."""
        if not self._dirty:
            return
        for j, arr in enumerate(self.stacked):
            for i, b in enumerate(self.blocks):
                params = [p for _, p in b.named_parameters()]
                params[j]._value = arr[i]
        self._dirty = False

    # -- step building ----------------------------------------------------
    def _opt_shardings(self, opt_state, specs, shapes):
        return self.plan.opt_state_shardings(opt_state, specs, shapes)

    def _build(self, x_sd, y_sd):
        opt = self.optimizer
        n_micro = self.n_micro
        ot_idx = self.ot_idx
        ot_set = set(ot_idx)
        staged, head, tail = self.staged, self.head, self.tail
        other_params, buffers = self.other_params, self.buffers
        loss_fn = self.loss_fn
        from ....optimizer.optimizer import apply_functional_with_clip
        pnames = [self.other_names[i] for i in ot_idx] + \
            [f"stacked.{n}" for n in self.t_names]

        def step(other_t, other_f, stacked_vals, buf_vals, opt_state, lr,
                 key, x, y):
            def loss_f(train_args):
                ot_vals, st_vals = train_args
                tv_map = dict(zip(ot_idx, ot_vals))
                fi = iter(other_f)
                full = [tv_map[i] if i in ot_set else next(fi)
                        for i in range(len(other_params))]
                olds = [t._value for t in other_params + buffers]
                for t, v in zip(other_params, full):
                    t._value = v
                for t, v in zip(buffers, buf_vals):
                    t._value = v
                try:
                    with _ag.suspend_tape(), rng_scope(key):
                        h = _apply_items(head, Tensor(x))
                        hv = h._value
                        B = hv.shape[0]
                        mb = B // n_micro
                        x_mb = hv.reshape(n_micro, mb, *hv.shape[1:])
                        y_mid = staged.apply(st_vals, x_mb)
                        y_mid = y_mid.reshape(B, *y_mid.shape[2:])
                        out = _apply_items(tail, Tensor(y_mid))
                        loss = loss_fn(out, Tensor(y))
                    new_buf = [t._value for t in buffers]
                    return loss._value, new_buf
                finally:
                    for t, v in zip(other_params + buffers, olds):
                        t._value = v

            (loss, new_buf), (g_ot, g_st) = jax.value_and_grad(
                loss_f, has_aux=True)((other_t, stacked_vals))
            train_vals = list(other_t) + list(stacked_vals)
            grads = list(g_ot) + list(g_st)
            new_vals, new_opt = apply_functional_with_clip(
                opt, train_vals, grads, opt_state, lr, param_names=pnames)
            k = len(other_t)  # lint: allow(len-on-traced) — python list of leaves, host-static
            return loss, new_vals[:k], new_vals[k:], new_buf, new_opt

        rep = self.plan.replicated()
        ot_sh = [self._other_sh[i] for i in ot_idx]
        of_sh = [self._other_sh[i] for i in range(len(self.other_params))
                 if i not in ot_set]
        specs = [self._other_specs[i] for i in ot_idx] + self._stacked_specs
        shapes = [tuple(self.other_params[i].shape) for i in ot_idx] + \
            [tuple(v.shape) for v in self.stacked]
        o_sh = self._opt_shardings(self.opt_state, specs, shapes)
        return jax.jit(
            step, donate_argnums=(0, 2, 3, 4),
            in_shardings=(ot_sh, of_sh, list(self._stacked_sh),
                          list(self._buf_sh), o_sh, rep, rep, x_sd, y_sd),
            out_shardings=(rep, ot_sh, list(self._stacked_sh),
                           list(self._buf_sh), o_sh))

    def train_step(self, x, y):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        x_sd = self.plan.input_sharding(xv.ndim)
        y_sd = self.plan.input_sharding(yv.ndim)
        xv = jax.device_put(xv, x_sd)
        yv = jax.device_put(yv, y_sd)

        ot_set = set(self.ot_idx)
        ot_vals = [self.other_params[i]._value for i in self.ot_idx]
        of_vals = [p._value for i, p in enumerate(self.other_params)
                   if i not in ot_set]
        buf_vals = [b._value for b in self.buffers]
        if self.opt_state is None:
            self.opt_state = self.optimizer.init_functional_state(
                ot_vals + self.stacked)
            specs = [self._other_specs[i] for i in self.ot_idx] + \
                self._stacked_specs
            shapes = [tuple(np.shape(v)) for v in ot_vals + self.stacked]
            o_sh = self._opt_shardings(self.opt_state, specs, shapes)
            self.opt_state = [
                {k: jax.device_put(v, s[k]) for k, v in st.items()}
                for st, s in zip(self.opt_state, o_sh)]
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)

        key = (tuple(xv.shape), str(xv.dtype), tuple(yv.shape),
               str(yv.dtype))
        if key not in self._step_cache:
            self._step_cache[key] = self._build(x_sd, y_sd)
        loss, new_ot, new_stacked, new_buf, new_opt = self._step_cache[key](
            ot_vals, of_vals, self.stacked, buf_vals, self.opt_state, lr,
            next_key(), xv, yv)
        for i, v in zip(self.ot_idx, new_ot):
            self.other_params[i]._value = v
        for b, v in zip(self.buffers, new_buf):
            b._value = v
        self.stacked = list(new_stacked)
        self.opt_state = new_opt
        self.optimizer._global_step += 1
        self._dirty = True
        return loss


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else {}) \
            or {}
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self._placement_plan = plan_from_hcg(hcg)
        self._stepper = None
        self.total_loss = None

    def forward(self, *args, **kwargs):
        self._sync()
        return self._layers(*args, **kwargs)

    def _sync(self):
        if self._stepper is not None:
            self._stepper.sync_to_layers()

    def state_dict(self, *a, **k):
        self._sync()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        out = self._layers.set_state_dict(sd, *a, **k)
        if self._stepper is not None:
            from ...pipeline import stack_block_params
            st = self._stepper
            fresh = stack_block_params(
                [[p._value for _, p in b.named_parameters()]
                 for b in st.blocks])
            st.stacked = [jax.device_put(v, s)
                          for v, s in zip(fresh, st._stacked_sh)]
            st._dirty = False
        return out

    def _compiled_ok(self, scaler):
        if not isinstance(self._layers, PipelineLayer):
            return False
        s, e = self._layers._homogeneous_span()
        if e - s < 2:
            return False
        # block-level buffers can't ride the stacked-params rotation
        mid = [l for l, _ in self._layers.run_function[s:e]]
        if any(True for b in mid for _ in b.named_buffers()):
            return False
        if scaler is not None:
            scale = getattr(scaler, "_scale", None)
            if scale is not None and float(scale) != 1.0:
                return False
        return True

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        """Micro-batched train step (reference signature).  data: [x, y]."""
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
        y = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        n_micro = self.accumulate_steps
        B = x.shape[0]
        assert B % n_micro == 0, f"batch {B} % micro {n_micro}"
        loss_f = loss_fn if loss_fn is not None else \
            getattr(self._layers, "_loss_fn", None)
        assert loss_f is not None, "PipelineParallel needs a loss_fn"

        if self._compiled_ok(scaler):
            if self._stepper is None or \
                    self._stepper.optimizer is not optimizer or \
                    self._stepper.loss_fn is not loss_f:
                self._stepper = _PipelineStepper(
                    self._layers, self._hcg, self._strategy, optimizer,
                    loss_f, n_micro)
            loss = self._stepper.train_step(x, y)
            if lr_scheduler is not None:
                lr_scheduler.step()
            self.total_loss = float(loss)
            return Tensor(np.asarray(self.total_loss, dtype="float32"))

        return self._train_batch_eager(x, y, optimizer, lr_scheduler,
                                       scaler, loss_f, n_micro)

    def _train_batch_eager(self, x, y, optimizer, lr_scheduler, scaler,
                           loss_f, n_micro):
        """Fallback: eager per-micro-batch gradient accumulation (FThenB
        math) for models without a pipelineable homogeneous run."""
        if self._stepper is not None:
            # never train two divergent copies: flush the compiled
            # stepper's state into the layer params and retire it (a
            # later compiled batch rebuilds from the layers; its
            # functional optimizer state restarts — mixing paths
            # mid-run is a correctness escape hatch, not a fast path)
            self._sync()
            self._stepper = None
        B = x.shape[0]
        mb = B // n_micro
        total = None
        for i in range(n_micro):
            xs = x[i * mb:(i + 1) * mb]
            ys = y[i * mb:(i + 1) * mb]
            out = self._layers(xs)
            loss = loss_f(out, ys)
            scaled = loss / n_micro
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = float(loss) if total is None else total + float(loss)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = total / n_micro
        return Tensor(np.asarray(self.total_loss, dtype="float32"))

    def eval_batch(self, data, compute_loss=True):
        self._sync()
        # predict-style batches carry no labels
        x, y = data if len(data) == 2 else (data[0], None)
        out = self._layers(x if isinstance(x, Tensor) else Tensor(x))
        if not compute_loss:
            return out
        if y is None:
            raise ValueError("eval_batch(compute_loss=True) needs [x, y]")
        loss_f = getattr(self._layers, "_loss_fn", None)
        return loss_f(out, y if isinstance(y, Tensor) else Tensor(y))
