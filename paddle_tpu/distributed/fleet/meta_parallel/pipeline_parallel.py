"""PipelineParallel wrapper (reference: fleet/meta_parallel/
pipeline_parallel.py — train_batch with FThenB/1F1B/interleaved schedules,
micro-batch splitting, P2P meta negotiation).

TPU-native: ``train_batch`` splits the batch into micro-batches and drives
the compiled step.  Two regimes:
- model exposes a homogeneous block run (PipelineLayer/GPT): the jitted
  step runs the SPMD pipeline (shard_map + ppermute rotation) — schedule
  and comm are inside ONE XLA program per micro-batch *group*;
- arbitrary model: micro-batches become gradient accumulation (same math
  as FThenB; the wavefront adds nothing without stage-sharded weights).
"""
import numpy as np

from ....nn.layer.layers import Layer
from ....framework.core import Tensor
from ...engine import plan_from_hcg

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else {}) \
            or {}
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self._placement_plan = plan_from_hcg(hcg)
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        """Micro-batched train step (reference signature).  data: [x, y]."""
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
        y = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
        n_micro = self.accumulate_steps
        B = x.shape[0]
        assert B % n_micro == 0, f"batch {B} % micro {n_micro}"
        mb = B // n_micro
        loss_f = loss_fn if loss_fn is not None else \
            getattr(self._layers, "_loss_fn", None)
        assert loss_f is not None, "PipelineParallel needs a loss_fn"

        total = None
        for i in range(n_micro):
            xs = x[i * mb:(i + 1) * mb]
            ys = y[i * mb:(i + 1) * mb]
            out = self._layers(xs)
            loss = loss_f(out, ys)
            scaled = loss / n_micro
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = float(loss) if total is None else total + float(loss)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = total / n_micro
        return Tensor(np.asarray(self.total_loss, dtype="float32"))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x if isinstance(x, Tensor) else Tensor(x))
        if not compute_loss:
            return out
        loss_f = getattr(self._layers, "_loss_fn", None)
        return loss_f(out, y if isinstance(y, Tensor) else Tensor(y))
