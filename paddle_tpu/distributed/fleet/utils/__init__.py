"""Fleet utils (reference: python/paddle/distributed/fleet/utils/)."""
from .recompute import recompute, recompute_sequential  # noqa: F401


def fused_allreduce_gradients(parameter_list, hcg):
    """Under GSPMD the DP grad reduction happens inside the compiled step;
    eager multi-process fallback averages via process_allgather."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    for p in parameter_list:
        if p._grad is not None:
            g = multihost_utils.process_allgather(p._grad)
            p._grad = g.mean(axis=0)


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS — filesystem client with the
    fleet checkpoint API shape."""

    def ls_dir(self, fs_path):
        import os
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_dir(self, fs_path):
        import os
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        import os
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        import os
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        import os
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        import os
        import shutil
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        import os
        os.rename(fs_src_path, fs_dst_path)

    def need_upload_download(self):
        return False

    @staticmethod
    def _copy(src, dst):
        import os
        import shutil
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            shutil.copy(src, dst)

    def upload(self, local_path, fs_path):
        self._copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self._copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        import os
        if not exist_ok and os.path.exists(fs_path):
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src, dst, overwrite=False, test_exists=False):
        import os
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        if not overwrite and os.path.exists(dst):
            raise FileExistsError(dst)
        os.replace(src, dst)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """reference: fleet/utils/fs.py HDFSClient (hadoop CLI wrapper).
    No hadoop binary exists in this environment; constructing raises
    with the documented alternative (LocalFS or a mounted path)."""

    def __init__(self, hadoop_home=None, configs=None, *a, **kw):
        raise RuntimeError(
            "HDFSClient needs a hadoop installation, which this "
            "environment does not provide; use LocalFS (or mount the "
            "remote store as a local path)")
