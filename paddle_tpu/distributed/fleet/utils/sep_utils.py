"""Segment-parallel ("sep") long-context attention utilities.

Reference analogue: the ``sep`` mesh axis in
python/paddle/distributed/fleet/base/topology.py — the reference's in-core
support is the axis + alltoall reshard (Ulysses); ring attention is made
first-class here per SURVEY.md §5.7/§7.

Two modes over the same seq-sharded activations (B, S/sep, H, D):
- ``sep_attention(..., mode="ulysses")`` — all_to_all head<->seq reshard
  around dense/flash attention (needs sep | num_heads).
- ``sep_attention(..., mode="ring")`` — ppermute KV rotation with online
  softmax (any head count, O(S/sep) activation memory).

These are Tensor-level and autograd-aware (jax differentiates through
ppermute/all_to_all); they must run inside a sep-axis shard_map — the
`RingFlashAttention` / `sep` paths of the hybrid engine arrange that.
"""
from ....framework.core import Tensor
from ....framework.autograd import call_op
from ....ops.ring_attention import ring_flash_attention, ulysses_attention

__all__ = ["sep_attention", "ring_attention", "split_inputs_sequence_dim",
           "RingFlashAttention", "set_sep_mesh"]

_SEP_AXIS = "sep"
_AMBIENT_MESH = [None]


def set_sep_mesh(mesh):
    """Register the jax Mesh carrying the sep axis.  sep_attention called
    OUTSIDE a shard_map (e.g. under the auto-parallel Engine's GSPMD
    stepper) wraps itself in a shard_map over this mesh; inside one it
    uses the ambient manual axis directly."""
    _AMBIENT_MESH[0] = mesh


def _in_manual_axis(axis):
    """True when tracing inside a shard_map/pmap that binds `axis`."""
    from ...collective import _in_named_trace
    return _in_named_trace(axis)


def sep_attention(query, key, value, is_causal=False, mode="ring",
                  sep_axis=_SEP_AXIS, scale=None):
    """Sequence-parallel scaled-dot-product attention on seq-sharded
    (B, S_local, H, D) tensors; full-softmax-exact over the global S.

    Inside a sep-axis shard_map (fleet hybrid engine) the collective
    rides the ambient manual axis.  Outside one, with a mesh registered
    via ``set_sep_mesh`` (the auto-parallel Engine does this when
    Strategy.sep_degree > 1), the call wraps itself in a shard_map that
    shards batch on the data axis and sequence on the sep axis."""
    q, k, v = [t if isinstance(t, Tensor) else Tensor(t)
               for t in (query, key, value)]
    if mode == "ring":
        fn = lambda a, b, c: ring_flash_attention(
            a, b, c, sep_axis, causal=bool(is_causal), scale=scale)
    elif mode == "ulysses":
        fn = lambda a, b, c: ulysses_attention(
            a, b, c, sep_axis, causal=bool(is_causal), scale=scale)
    else:
        raise ValueError(f"unknown sep attention mode {mode!r}")
    if _in_manual_axis(sep_axis):
        return call_op(fn, q, k, v)
    mesh = _AMBIENT_MESH[0]
    if mesh is None or sep_axis not in mesh.axis_names:
        raise RuntimeError(
            "sep_attention: not inside a shard_map over the sep axis and "
            "no sep mesh registered — run under the fleet hybrid engine, "
            "an explicit shard_map, or an Engine with sep_degree > 1 "
            "(which calls set_sep_mesh)")
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map as _smap
    except ImportError:
        from jax.experimental.shard_map import shard_map as _smap
    batch = tuple(a for a in ("data", "sharding")
                  if a in mesh.axis_names and mesh.shape[a] > 1) or None
    spec = P(batch, sep_axis, None, None)
    wrapped = _smap(fn, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    return call_op(wrapped, q, k, v)


def ring_attention(query, key, value, is_causal=False, sep_axis=_SEP_AXIS):
    return sep_attention(query, key, value, is_causal, "ring", sep_axis)


def split_inputs_sequence_dim(inputs, rank, degree, axis=1):
    """Shard a full-sequence batch for this sep rank (the reference splits
    inputs along seq before feeding sep-parallel models)."""
    from ....tensor.manipulation import split
    if degree <= 1:
        return inputs
    return split(inputs, degree, axis=axis)[rank]


class RingFlashAttention:
    """PyLayer-shaped facade matching the reference-era custom-op API."""

    @staticmethod
    def apply(q, k, v, causal=False, sep_axis=_SEP_AXIS):
        return sep_attention(q, k, v, is_causal=causal, mode="ring",
                             sep_axis=sep_axis)
