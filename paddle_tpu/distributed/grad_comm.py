"""Communication-efficient gradient reduction for data-parallel steps.

The GSPMD data-parallel path lets XLA insert one gradient all-reduce per
parameter wherever its scheduler likes.  This module is the explicit
twin used by the hapi compiled stepper's shard_map path; it implements:

- **Bucketed, backward-overlapped all-reduce** (PAPERS.md "T3"): the
  grad tree is partitioned into size-targeted buckets in *reverse*
  parameter order — backward produces the last layers' gradients first,
  so the first buckets' reduces depend only on values available early
  in backward and the latency-hiding scheduler can run them under the
  remaining backward compute.  The final bucket (first layers' grads)
  completes with backward itself and cannot overlap; the structural
  ``pt_collective_overlap_fraction`` gauge reports the overlap-eligible
  byte share.
- **Opt-in quantized all-reduce** (PAPERS.md "EQuARX"): ``bf16`` casts
  the bucket for the wire; ``int8``/``fp8`` run the two-phase scheme —
  chunkwise absmax-scaled quantize → ``all_to_all`` (each rank receives
  its shard from every peer in the narrow dtype) → dequantized fp32
  partial sums → requantize → ``all_gather``.  The wire never carries a
  partially-summed narrow value, so there is no int8 overflow and the
  documented error is pure quantization error (see
  docs/DISTRIBUTED.md, "accuracy contract").
- **ZeRO-1 as a flag** (PAPERS.md "Automatic Cross-Replica Sharding of
  Weight Update"): ``grad_comm_configs={"zero1": True}`` does NOT use
  this module's reducer — it routes the PlacementPlan to
  ``level="os"`` with the *data* axis as the fsdp axis, so the existing
  plan-based stepper shards the optimizer state across replicas and
  GSPMD emits the reduce-scatter + all-gather wire pattern.

Bytes on the wire flow into the PR 5 ``pt_collective_*`` counters from
static shape/dtype metadata (per *tracing* inside jit, like every other
traced collective — the catalog documents that honestly).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import observability as _obs
from ..analysis import jit_surface, register_jit_surface
from .collective import _telemetry

__all__ = ["GradCommConfig", "BucketPlan", "plan_buckets",
           "build_grad_reducer"]

# the traced reducers are nested defs a decorator can't reach; mirrored
# in analysis.allowlist.EXTRA_JIT_SURFACES
for _qual in ("build_grad_reducer.reduce",
              "_build_quant_reduce.quant_reduce"):
    register_jit_surface(__name__, _qual)

_QUANT_MODES = (None, "bf16", "int8", "fp8")
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)


class GradCommConfig:
    """Normalized ``DistributedStrategy.grad_comm_configs``.

    ``enabled`` turns on the explicit bucketed reducer (shard_map
    stepper path); ``zero1`` instead reroutes the plan-based path.  The
    two are mutually exclusive: the explicit reducer assumes replicated
    optimizer state, ZeRO-1 shards it — combining them would reduce
    every gradient twice.
    """

    def __init__(self, enabled=True, bucket_mb=32.0, overlap=True,
                 quantize=None, quant_chunk=65536, zero1=False):
        if quantize not in _QUANT_MODES:
            raise ValueError(
                f"grad_comm: unknown quantize mode {quantize!r} "
                f"(choose from {_QUANT_MODES})")
        if enabled and zero1:
            raise ValueError(
                "grad_comm: zero1 and the bucketed/quantized explicit "
                "reducer are mutually exclusive — zero1 shards the "
                "weight update on the plan-based (GSPMD) path while the "
                "reducer assumes a replicated update; enable one or the "
                "other")
        self.fp8_fallback = False
        if quantize == "fp8" and _FP8_DTYPE is None:
            # "fp8 where available": older jax has no fp8 dtype — keep
            # the run alive on the int8 path and say so
            quantize = "int8"
            self.fp8_fallback = True
        self.enabled = bool(enabled)
        self.bucket_mb = float(bucket_mb)
        self.overlap = bool(overlap)
        self.quantize = quantize
        self.quant_chunk = max(int(quant_chunk), 1)
        self.zero1 = bool(zero1)

    @classmethod
    def from_strategy(cls, strategy):
        """None unless the strategy asks for grad_comm or zero1."""
        if strategy is None:
            return None
        on = bool(getattr(strategy, "grad_comm", False))
        cfgs = dict(getattr(strategy, "grad_comm_configs", None) or {})
        zero1 = bool(cfgs.get("zero1", False))
        if not on and not zero1:
            return None
        bucket_mb = cfgs.get("bucket_mb")
        if bucket_mb is None:
            bucket_mb = getattr(strategy, "fuse_grad_size_in_MB", 32)
        return cls(enabled=on, bucket_mb=bucket_mb,
                   overlap=cfgs.get("overlap", True),
                   quantize=cfgs.get("quantize"),
                   quant_chunk=cfgs.get("quant_chunk", 65536),
                   zero1=zero1)

    def describe(self):
        return (f"GradCommConfig(enabled={self.enabled}, "
                f"bucket_mb={self.bucket_mb}, overlap={self.overlap}, "
                f"quantize={self.quantize}, zero1={self.zero1})")


class BucketPlan:
    """Size-targeted partition of the grad list (reverse param order)."""

    def __init__(self, buckets, nbytes):
        self.buckets = buckets          # list of index lists
        self.nbytes = nbytes            # bytes per bucket
        self.total_bytes = sum(nbytes)

    @property
    def overlap_fraction(self):
        """Byte share whose reduce can hide under remaining backward
        compute: everything but the final bucket, which completes with
        backward itself.  Structural (from the plan), not measured."""
        if len(self.buckets) <= 1 or self.total_bytes == 0:
            return 0.0
        return 1.0 - self.nbytes[-1] / self.total_bytes

    def __repr__(self):
        return (f"BucketPlan(n={len(self.buckets)}, "
                f"bytes={self.nbytes})")


def plan_buckets(shapes, dtypes, bucket_bytes):
    """Greedy partition in reverse parameter order: walk params from the
    last (whose grads backward produces first), close a bucket once it
    reaches ``bucket_bytes``.  A single oversized tensor gets its own
    bucket rather than splitting (splitting one array across reduces
    buys nothing — its grad materializes all at once)."""
    buckets, nbytes = [], []
    cur, cur_b = [], 0
    for i in reversed(range(len(shapes))):
        b = int(np.prod(shapes[i], dtype=np.int64) or 1) \
            * jnp.dtype(dtypes[i]).itemsize
        cur.append(i)
        cur_b += b
        if cur_b >= bucket_bytes:
            buckets.append(cur)
            nbytes.append(cur_b)
            cur, cur_b = [], 0
    if cur:
        buckets.append(cur)
        nbytes.append(cur_b)
    return BucketPlan(buckets, nbytes)


def _to_narrow(x, mode):
    """Quantize a pre-scaled fp32 array onto the wire dtype."""
    if mode == "int8":
        return jnp.clip(jnp.round(x), -127.0, 127.0).astype(jnp.int8)
    return jnp.clip(x, -448.0, 448.0).astype(_FP8_DTYPE)


def _quant_qmax(mode):
    return 127.0 if mode == "int8" else 448.0


def _build_quant_reduce(axis_name, world, chunk, mode):
    """Build the EQuARX-pattern two-phase quantized all-reduce of a flat
    fp32 vector, with topology (``world``), chunking and wire mode fixed
    at build time (trace-time constants — every rank traces the same
    collective sequence).  Phase 1: chunkwise absmax-quantize the
    per-destination shards and exchange them with ONE narrow-dtype
    ``all_to_all``; the receiver dequantizes and sums in fp32, so no
    narrow value ever holds a partial sum (no int8 overflow at any world
    size).  Phase 2: requantize the reduced shard and ``all_gather`` it
    back.  Scales ride as fp32 sidecars (1 per ``chunk`` elements).
    Returns the SUM (caller applies the 1/world mean)."""
    qmax = _quant_qmax(mode)

    def quant_reduce(vec):
        n = vec.shape[0]
        per = -(-n // world)    # ceil: elements destined per rank
        # ``chunk`` caps the scale-group size; the shard is split into
        # equal groups of at most that, NOT rounded up to a chunk
        # multiple — rounding pads a 69k-element shard to 2 full 64k
        # chunks (88% dead wire bytes; a 256KB bucket even came out
        # LARGER than its fp32 psum before this)
        g = -(-per // min(chunk, per))
        c = -(-per // g)
        shard = g * c
        total = shard * world
        if total > n:           # static: shape metadata + build consts
            vec = jnp.concatenate(
                [vec, jnp.zeros((total - n,), vec.dtype)])
        x = vec.reshape(world, shard // c, c)
        amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        scale = jnp.maximum(amax, 1e-30) / qmax
        q = _to_narrow(x / scale, mode)
        _telemetry("grad_quant_all_to_all", (q, scale))
        q_t = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                             tiled=True)
        s_t = lax.all_to_all(scale, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)
        partial = jnp.sum(q_t.astype(jnp.float32) * s_t, axis=0)
        amax2 = jnp.max(jnp.abs(partial), axis=-1, keepdims=True)
        scale2 = jnp.maximum(amax2, 1e-30) / qmax
        q2 = _to_narrow(partial / scale2, mode)
        _telemetry("grad_quant_all_gather", (q2, scale2))
        q2_all = lax.all_gather(q2, axis_name)
        s2_all = lax.all_gather(scale2, axis_name)
        out = (q2_all.astype(jnp.float32) * s2_all).reshape(total)
        return out[:n]

    return quant_reduce


@jit_surface
def _psum_reduce(vec, axis_name):
    _telemetry("grad_bucket_psum", vec)
    return lax.psum(vec, axis_name)


@jit_surface
def _bf16_reduce(vec, axis_name):
    """Half-width wire: cast the bucket to bf16 for the reduce.  The
    accumulation itself happens in bf16 (XLA's psum dtype follows the
    operand) — cheapest mode, loosest contract."""
    w = vec.astype(jnp.bfloat16)
    _telemetry("grad_bucket_psum_bf16", w)
    return lax.psum(w, axis_name).astype(vec.dtype)


def build_grad_reducer(shapes, dtypes, cfg, axis_name, world):
    """Build the traced ``reduce(grads) -> mean_grads`` closure for one
    parameter list (trainable order).  All partitioning/dispatch
    decisions happen HERE at build time from static shapes and config —
    the traced body contains no mode conditionals, so every rank traces
    the identical collective sequence (collective-order lint clean by
    construction).  Returns ``(reduce, plan)``."""
    bucket_bytes = max(int(cfg.bucket_mb * (1 << 20)), 1)
    if not cfg.overlap:
        bucket_bytes = 1 << 62          # one monolithic bucket
    plan = plan_buckets(shapes, dtypes, bucket_bytes)
    mode = cfg.quantize
    chunk = cfg.quant_chunk
    if _obs.enabled():
        _obs.set_gauge("pt_collective_grad_buckets", len(plan.buckets))
        _obs.set_gauge("pt_collective_overlap_fraction",
                       plan.overlap_fraction)
        # analytical bytes ONE step puts on the wire under this plan
        # (static shapes + wire mode — no readback): quantized modes
        # carry ~1 byte/element plus one fp32 scale per quant chunk;
        # joined against compile-telemetry FLOPs by `report --roofline`
        n_elts = sum(int(np.prod(s, dtype=np.int64) or 1)
                     for s in shapes)
        item = {"int8": 1, "fp8": 1, "bf16": 2}.get(mode, 4)
        wire = n_elts * item
        if mode in ("int8", "fp8"):
            wire += -(-n_elts // max(chunk, 1)) * 4
        _obs.set_gauge("pt_collective_wire_bytes_per_step", wire)
    inv_world = 1.0 / float(world)
    if mode in ("int8", "fp8"):
        reduce_vec = _build_quant_reduce(axis_name, world, chunk, mode)
    elif mode == "bf16":
        def reduce_vec(v):
            return _bf16_reduce(v, axis_name)
    else:
        def reduce_vec(v):
            return _psum_reduce(v, axis_name)
    meta = []
    for idxs in plan.buckets:
        sizes = [int(np.prod(shapes[i], dtype=np.int64) or 1)
                 for i in idxs]
        rdtype = jnp.result_type(*[dtypes[i] for i in idxs]) \
            if len(idxs) > 1 else jnp.dtype(dtypes[idxs[0]])
        if mode in ("int8", "fp8"):
            rdtype = jnp.promote_types(rdtype, jnp.float32)
        meta.append((idxs, sizes, rdtype))

    def reduce(grads):
        out = list(grads)
        for idxs, sizes, rdtype in meta:
            vec = jnp.concatenate(
                [jnp.ravel(grads[i]).astype(rdtype) for i in idxs]) \
                if len(idxs) > 1 else \
                jnp.ravel(grads[idxs[0]]).astype(rdtype)
            vec = reduce_vec(vec) * inv_world   # ring-sum -> DP mean
            off = 0
            for i, sz in zip(idxs, sizes):
                out[i] = vec[off:off + sz].reshape(
                    tuple(shapes[i])).astype(jnp.dtype(dtypes[i]))
                off += sz
        return out

    return reduce, plan
