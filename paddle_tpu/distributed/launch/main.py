"""Launcher CLI (reference: python/paddle/distributed/launch/main.py ==
``fleetrun``: spawn per-device workers, set PADDLE_* env, watch loop,
restart on failure).

TPU-native: ONE process per host drives all local chips (SPMD), so
``--nnodes`` is the only real fan-out; per-host we spawn a single worker
(vs the reference's one-per-GPU).  The watch loop + restart-with-resume
survives worker crashes; rendezvous is the JAX coordinator (the reference's
TCPStore master).

Usage:  python -m paddle_tpu.distributed.launch [--nnodes N] [--node_rank R]
            [--master host:port] [--max_restart K] script.py [args...]
"""
import argparse
import os
import signal
import subprocess
import sys
import time


def _parse():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count (N or min:max for elastic)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per host (1 on TPU: SPMD drives all chips)")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="accepted for compat; chip selection is automatic")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _worker_env(args, local_rank):
    env = dict(os.environ)
    nnodes = int(str(args.nnodes).split(":")[0])
    nproc = args.nproc_per_node
    world = nnodes * nproc
    rank = args.node_rank * nproc + local_rank
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    if args.master:
        env["PADDLE_MASTER"] = args.master
    env["PADDLE_CURRENT_ENDPOINT"] = \
        f"{os.environ.get('POD_IP', '127.0.0.1')}:{6170 + local_rank}"
    return env


def main():
    args = _parse()
    os.makedirs(args.log_dir, exist_ok=True)
    procs = {}
    restarts = {i: 0 for i in range(args.nproc_per_node)}
    logs = {}

    def start(local_rank):
        log_path = os.path.join(args.log_dir, f"workerlog.{local_rank}")
        logf = open(log_path, "ab", buffering=0)
        logs[local_rank] = logf
        cmd = [sys.executable, args.script] + args.script_args
        p = subprocess.Popen(cmd, env=_worker_env(args, local_rank),
                             stdout=logf, stderr=subprocess.STDOUT)
        procs[local_rank] = p
        print(f"[launch] started worker {local_rank} pid={p.pid} "
              f"log={log_path}", flush=True)

    def shutdown(signum=None, frame=None):
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        t0 = time.time()
        while any(p.poll() is None for p in procs.values()) and \
                time.time() - t0 < 10:
            time.sleep(0.2)
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        sys.exit(1 if signum else 0)

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)

    for i in range(args.nproc_per_node):
        start(i)

    # watch loop (reference: controllers/controller.py::watch)
    while True:
        alive = 0
        for i, p in list(procs.items()):
            ret = p.poll()
            if ret is None:
                alive += 1
            elif ret != 0:
                if restarts[i] < args.max_restart:
                    restarts[i] += 1
                    print(f"[launch] worker {i} exited rc={ret}; restart "
                          f"{restarts[i]}/{args.max_restart}", flush=True)
                    start(i)
                    alive += 1
                else:
                    print(f"[launch] worker {i} failed rc={ret}; giving up",
                          flush=True)
                    shutdown()
        if alive == 0:
            break
        time.sleep(1)
    print("[launch] all workers finished", flush=True)


if __name__ == "__main__":
    main()
