"""Launcher CLI (reference: python/paddle/distributed/launch/main.py ==
``fleetrun``: spawn per-device workers, set PADDLE_* env, watch loop,
restart on failure).

TPU-native: ONE process per host drives all local chips (SPMD), so
``--nnodes`` is the only real fan-out; per-host we spawn a single worker
(vs the reference's one-per-GPU).  The watch loop + restart-with-resume
survives worker crashes; rendezvous is the JAX coordinator (the reference's
TCPStore master).  With ``--nnodes min:max`` the launcher also runs the
elastic membership watch: the registry store listens on master_port+1 (the
master port itself belongs to the workers' rendezvous), and on membership
change workers are relaunched with rank/world recomputed from the live
member set.
"""
import argparse
import os
import signal
import subprocess
import sys
import time

from ...framework import failpoints as _fp
from ...framework.backoff import jittered_delay
from ...framework.preemption import PREEMPTED_EXIT_CODE
from ..fleet import elastic as _elastic_mod
from ..fleet.elastic import ElasticManager, ElasticStatus

# restart hygiene: sleep with exponential backoff between restarts of the
# same worker (a crash-looping script must not spin the host), and forgive
# the restart budget once a worker has run stably for this long — a job
# that hiccups once a day should never exhaust max_restart
_RESTART_BACKOFF_BASE = 1.0
_RESTART_BACKOFF_CAP = 60.0
_STABLE_WINDOW_S = float(os.environ.get("PADDLE_STABLE_WINDOW", "60"))


def _restart_backoff(n_restarts):
    """Jittered exponential backoff (seconds) before restart N."""
    return jittered_delay(max(n_restarts - 1, 0),
                          _RESTART_BACKOFF_BASE, _RESTART_BACKOFF_CAP)


class _RestartPolicy:
    """Per-worker restart accounting shared by the collective and PS
    watch loops: backoff deadlines (never blocking the loop),
    stable-window budget forgiveness, and a preemption budget separate
    from (and more generous than) the crash budget."""

    def __init__(self, max_restart):
        self.max_restart = max_restart
        self.restarts = {}
        self.preempts = {}
        self.started_at = {}
        self.pending = {}       # key -> earliest restart time

    def note_start(self, key):
        self.started_at[key] = time.time()

    def is_pending(self, key):
        return key in self.pending

    def has_pending(self):
        return bool(self.pending)

    def pop_due(self, now):
        """Keys whose backoff has elapsed; removed from pending."""
        due = [k for k, t in self.pending.items() if now >= t]
        for k in due:
            del self.pending[k]
        return due

    def reset_all(self):
        self.pending.clear()
        self.restarts.clear()
        self.preempts.clear()

    def on_exit(self, key, ret, now, label):
        """Handle a non-zero exit: schedule a restart (returns
        ``"restart"``, key parked in ``pending``) or ``"give_up"``."""
        # stable-window forgiveness, with the bar rising per CRASH on
        # record: a fixed window would let a worker that deterministically
        # crashes just past it restart forever, never exhausting
        # max_restart — scaling by crash count guarantees any fixed
        # crash interval eventually stops qualifying.  Preemptions do
        # NOT raise the bar: a pool legitimately evicting workers every
        # few minutes must keep qualifying for forgiveness, or a healthy
        # checkpoint-and-resume job would exhaust the preempt budget.
        crash_history = self.restarts.get(key, 0)
        window = _STABLE_WINDOW_S * (1 + crash_history)
        if (crash_history or self.preempts.get(key)) and \
                now - self.started_at.get(key, 0) >= window:
            print(f"[launch] {label} was stable for >{window:.0f}s; "
                  "resetting its restart budget", flush=True)
            self.restarts[key] = 0
            self.preempts[key] = 0
        if ret == PREEMPTED_EXIT_CODE:
            # the worker saved an emergency checkpoint and asked to be
            # relaunched (framework/preemption.py contract): restart
            # with resume, without charging the crash budget — but a
            # worker that does nothing except exit 71 is a bug, so a
            # generous separate budget still bounds the loop
            self.preempts[key] = self.preempts.get(key, 0) + 1
            if self.preempts[key] > max(3 * self.max_restart, 10):
                print(f"[launch] {label} preempted {self.preempts[key]} "
                      "times without a stable run; giving up", flush=True)
                return "give_up"
            backoff = _restart_backoff(min(self.preempts[key], 3))
            print(f"[launch] {label} preempted (rc={ret}); restarting "
                  f"with resume from its latest checkpoint in "
                  f"{backoff:.1f}s", flush=True)
        else:
            if self.restarts.get(key, 0) >= self.max_restart:
                print(f"[launch] {label} failed rc={ret}; giving up",
                      flush=True)
                return "give_up"
            self.restarts[key] = self.restarts.get(key, 0) + 1
            backoff = _restart_backoff(self.restarts[key])
            print(f"[launch] {label} exited rc={ret}; restart "
                  f"{self.restarts[key]}/{self.max_restart} in "
                  f"{backoff:.1f}s", flush=True)
        self.pending[key] = now + backoff
        return "restart"


def _parse():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count (N or min:max for elastic)")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per host (1 on TPU: SPMD drives all chips)")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective",
                   help="collective | ps")
    p.add_argument("--server_num", type=int, default=0,
                   help="PS mode: number of parameter servers to spawn")
    p.add_argument("--trainer_num", type=int, default=None,
                   help="PS mode: number of trainer workers to spawn")
    p.add_argument("--servers", type=str, default="",
                   help="PS mode: comma list of host:port server endpoints"
                        " (default 127.0.0.1 with sequential ports)")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="accepted for compat; chip selection is automatic")
    p.add_argument("--ckpt_root", type=str,
                   default=os.environ.get("PADDLE_CKPT_ROOT", ""),
                   help="manifest-checkpoint root for elastic resume: "
                        "exported to every worker as PADDLE_CKPT_ROOT "
                        "AND PADDLE_RESUME_ROOT, so the trainer script "
                        "resumes from the newest committed manifest "
                        "step via Model.fit(resume=) — an empty root "
                        "is a fresh start, making resume a property of "
                        "the on-disk state rather than launcher-local "
                        "restart history")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _worker_env(args, local_rank, membership):
    """membership: {"node_index": i, "n_nodes": n, "endpoints": [...]}
    — static from --node_rank/--nnodes, or live from the elastic store.
    With a ``--ckpt_root`` configured, EVERY start points the worker at
    the manifest root via ``PADDLE_RESUME_ROOT``: the trainer passes it
    to ``Model.fit(resume=...)``, which treats an empty root as a fresh
    start — so whether this launch resumes is decided by the on-disk
    checkpoint state, not launcher-local restart history (a freshly
    rebooted launcher rejoining an elastic job must restore the same
    checkpoint its surviving peers do, or ranks diverge)."""
    env = dict(os.environ)
    nproc = args.nproc_per_node
    world = membership["n_nodes"] * nproc
    rank = membership["node_index"] * nproc + local_rank
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    if args.master:
        env["PADDLE_MASTER"] = args.master
    if membership.get("endpoints"):
        # one endpoint per TRAINER: expand each node's base port by
        # local_rank so len(endpoints) == world size
        expanded = []
        for ep in membership["endpoints"]:
            if ":" in ep:
                h, prt = ep.rsplit(":", 1)
                # ':0' is ElasticManager.start()'s "no port" placeholder,
                # not a real base — fall back like the empty case
                base = int(prt) if prt and int(prt) != 0 else 6170
            else:
                h, base = ep, 6170
            for lr in range(nproc):
                expanded.append(f"{h}:{base + lr}")
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(expanded)
    env["PADDLE_CURRENT_ENDPOINT"] = \
        f"{os.environ.get('POD_IP', '127.0.0.1')}:{6170 + local_rank}"
    if getattr(args, "ckpt_root", ""):
        env["PADDLE_CKPT_ROOT"] = args.ckpt_root
        env["PADDLE_RESUME_ROOT"] = args.ckpt_root
    return env


def _note_reshard(old_np, new_np, root):
    """Book a restart-with-resume at a changed world size: fire the
    ``elastic.reshard`` failpoint, count ``pt_checkpoint_reshard_total``
    and emit the ``elastic_reshard`` guardian event — the observable
    record that the job is resuming on different capacity."""
    if _fp._ACTIVE:
        _fp.fire(_elastic_mod.FP_RESHARD)
    try:
        from ... import observability as _obs
        if _obs.enabled():
            _obs.inc("pt_checkpoint_reshard_total", kind="relaunch")
    except Exception:
        pass
    try:
        from ...framework import guardian as _guardian
        _guardian.emit("elastic_reshard", old_np=int(old_np),
                       new_np=int(new_np), root=str(root or ""),
                       source="relaunch")
    except Exception:
        print(f"[launch] elastic reshard: np {old_np} -> {new_np} "
              f"(resume root {root!r})", flush=True)


def _elastic_registry_endpoint(master):
    """Elastic store rides master_port+1: the master port itself is the
    workers' rendezvous (jax coordinator / MasterStore) and must stay
    free for them."""
    host, _, port = master.partition(":")
    return host or "127.0.0.1", int(port or 6768) + 1


def _setup_elastic(args):
    """min:max nnodes + a master endpoint → store-backed ElasticManager
    (node 0 hosts the registry store, mirroring the reference's ETCD)."""
    if ":" not in str(args.nnodes) or not args.master:
        return None
    from ..store import TCPStore
    host, port = _elastic_registry_endpoint(args.master)
    store = None
    if args.node_rank == 0:
        store = TCPStore(host, port, is_master=True)
    mgr = ElasticManager(np=args.nnodes, store=store,
                         master=f"{host}:{port}" if store is None else None)
    mgr.start(endpoint=f"{os.environ.get('POD_IP', '127.0.0.1')}:6170")
    print(f"[launch] elastic: np={args.nnodes} registered as node "
          f"{mgr._node_id}", flush=True)
    # gate the first launch on quorum: starting below min_np would train
    # with the wrong world size
    got = mgr.wait_for_np()
    if not got:
        print(f"[launch] elastic: quorum of {mgr.min_np} nodes not reached "
              f"within {mgr.elastic_timeout}s (observed {int(got)} "
              f"member(s)); aborting", flush=True)
        mgr.stop()
        sys.exit(1)
    return mgr


def _elastic_membership(elastic, args):
    """Live rank/world from the member set (node order = node-id order).
    node_index is None when this node was capped out by max_np — it must
    stand by, not train with a colliding rank."""
    members = elastic._members()
    ids = sorted(members)
    try:
        idx = ids.index(elastic._node_id)
    except ValueError:
        idx = None
    return {"node_index": idx, "n_nodes": max(len(ids), 1),
            "endpoints": [members[i] for i in ids]}


def _launch_ps(args):
    """PS-mode controller (reference: launch/controllers/ps.py): spawn
    ``server_num`` PSERVER processes + ``trainer_num`` TRAINER processes
    with the PADDLE_* role env, watch, restart trainers on failure
    (servers are stateful — a dead server fails the job)."""
    import socket

    os.makedirs(args.log_dir, exist_ok=True)
    n_srv = args.server_num or 1
    n_trn = args.trainer_num if args.trainer_num is not None else 1
    if args.servers:
        endpoints = [e for e in args.servers.split(",") if e]
    else:
        # hold every probe socket until all ports are drawn, or the
        # kernel can hand the same ephemeral port out twice
        probes = []
        endpoints = []
        for _ in range(n_srv):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            probes.append(s)
            endpoints.append(f"127.0.0.1:{s.getsockname()[1]}")
        for s in probes:
            s.close()
    ep_list = ",".join(endpoints)
    procs, logs = {}, {}
    policy = _RestartPolicy(args.max_restart)

    def start(kind, idx):
        key = (kind, idx)
        log_path = os.path.join(args.log_dir, f"{kind}log.{idx}")
        if key in logs:
            logs[key].close()        # restart: don't leak the old handle
        logf = open(log_path, "ab", buffering=0)
        logs[key] = logf
        env = dict(os.environ)
        # scrub any collective-mode env leaked from the parent (a PS
        # worker inheriting PADDLE_MASTER/TRAINER_ENDPOINTS would try a
        # collective rendezvous nobody is serving)
        for stale in ("PADDLE_MASTER", "PADDLE_TRAINER_ENDPOINTS",
                      "PADDLE_CURRENT_ENDPOINT", "PADDLE_NODE_RANK",
                      "PADDLE_LOCAL_RANK", "PADDLE_TRAINER_ID",
                      "TRAINING_ROLE", "POD_IP", "PADDLE_PORT"):
            env.pop(stale, None)
        env["PADDLE_PSERVERS_IP_PORT_LIST"] = ep_list
        env["PADDLE_TRAINERS_NUM"] = str(n_trn)
        if kind == "server":
            host, _, port = endpoints[idx].rpartition(":")
            env["TRAINING_ROLE"] = "PSERVER"
            env["POD_IP"] = host or "127.0.0.1"
            env["PADDLE_PORT"] = port
        else:
            env["TRAINING_ROLE"] = "TRAINER"
            env["PADDLE_TRAINER_ID"] = str(idx)
        cmd = [sys.executable, args.script] + args.script_args
        p = subprocess.Popen(cmd, env=env, stdout=logf,
                             stderr=subprocess.STDOUT)
        procs[key] = p
        policy.note_start(key)
        print(f"[launch] started {kind} {idx} pid={p.pid} log={log_path}",
              flush=True)

    def stop_all(code):
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        t0 = time.time()
        while any(p.poll() is None for p in procs.values()) and \
                time.time() - t0 < 10:
            time.sleep(0.2)
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        sys.exit(code)

    for i in range(n_srv):
        start("server", i)
    for i in range(n_trn):
        start("trainer", i)

    while True:
        trainers_alive = 0
        now = time.time()
        for kind, idx in policy.pop_due(now):   # backoff elapsed
            start(kind, idx)
        for (kind, idx), p in list(procs.items()):
            key = (kind, idx)
            if policy.is_pending(key):
                trainers_alive += 1      # restart-pending counts as live
                continue
            ret = p.poll()
            if ret is None:
                if kind == "trainer":
                    trainers_alive += 1
                continue
            if kind == "server":
                # ANY server exit while trainers still run is fatal —
                # rc==0 (script forgot run_server) strands trainers on a
                # dead endpoint with a misleading eventual diagnosis
                print(f"[launch] server {idx} exited rc={ret} before the "
                      "trainers finished; aborting", flush=True)
                stop_all(1)
            if kind == "trainer" and ret != 0:
                if policy.on_exit(key, ret, now,
                                  f"trainer {idx}") == "give_up":
                    stop_all(1)
                trainers_alive += 1
        if trainers_alive == 0 and \
                all(p.poll() is not None or k[0] == "server"
                    for k, p in procs.items()):
            # every trainer finished cleanly: job done, retire servers
            print("[launch] all trainers finished; stopping servers",
                  flush=True)
            for (kind, _), p in procs.items():
                if kind == "server" and p.poll() is None:
                    p.terminate()
            for p in procs.values():
                if p.poll() is None:
                    p.wait()
            return
        time.sleep(0.5)


def main():
    args = _parse()
    if args.run_mode == "ps" or args.server_num > 0:
        _launch_ps(args)
        return
    os.makedirs(args.log_dir, exist_ok=True)
    procs = {}
    policy = _RestartPolicy(args.max_restart)
    logs = {}
    elastic = _setup_elastic(args)
    membership = {"node_index": args.node_rank,
                  "n_nodes": int(str(args.nnodes).split(":")[0]),
                  "endpoints": []}
    if elastic is not None:
        membership = _elastic_membership(elastic, args)
        if membership["node_index"] is None:
            print("[launch] elastic: this node is beyond max_np; exiting",
                  flush=True)
            elastic.stop()
            sys.exit(1)

    def start(local_rank):
        log_path = os.path.join(args.log_dir, f"workerlog.{local_rank}")
        if local_rank in logs:
            logs[local_rank].close()  # restart: don't leak the old handle
        logf = open(log_path, "ab", buffering=0)
        logs[local_rank] = logf
        cmd = [sys.executable, args.script] + args.script_args
        p = subprocess.Popen(cmd, env=_worker_env(args, local_rank,
                                                  membership),
                             stdout=logf, stderr=subprocess.STDOUT)
        procs[local_rank] = p
        policy.note_start(local_rank)
        print(f"[launch] started worker {local_rank} pid={p.pid} "
              f"rank={membership['node_index'] * args.nproc_per_node + local_rank} "
              f"world={membership['n_nodes'] * args.nproc_per_node} "
              f"log={log_path}", flush=True)

    def stop_workers():
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        t0 = time.time()
        while any(p.poll() is None for p in procs.values()) and \
                time.time() - t0 < 10:
            time.sleep(0.2)
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()                 # reap — no zombies

    def shutdown(signum=None, frame=None, code=None):
        if elastic is not None:
            elastic.stop()               # mark this node dead immediately
        stop_workers()
        sys.exit(code if code is not None else (1 if signum else 0))

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)

    for i in range(args.nproc_per_node):
        start(i)

    # watch loop (reference: controllers/controller.py::watch +
    # elastic/manager.py membership watch)
    holding = False
    hold_since = None
    # the world size workers are ACTUALLY running at — `membership` is
    # recomputed on every hold/restart pass (including capped-out holds
    # that never relaunch), so the reshard event's old_np must come
    # from the last world that really ran, not the latest snapshot
    active_world = membership["n_nodes"] * args.nproc_per_node
    while True:
        status = elastic.watch() if elastic is not None else None
        if status == ElasticStatus.HOLD:
            # below min nodes: pause failure accounting — crashed workers
            # stay down (their restart budget untouched) until membership
            # recovers (RESTART) or the elastic timeout expires
            if not holding:
                print("[launch] elastic: below min nodes, holding",
                      flush=True)
                holding = True
                hold_since = time.time()
            if time.time() - hold_since > elastic.elastic_timeout * 4:
                print("[launch] elastic: membership never recovered; "
                      "giving up", flush=True)
                shutdown(code=1)
            # still reap finished workers so a completed job can exit —
            # but a worker parked awaiting its restart-backoff deadline
            # is dead-by-design, not "done"
            if not policy.has_pending() and \
                    all(p.poll() is not None for p in procs.values()):
                rcs = [p.returncode for p in procs.values()]
                code = 0 if all(r == 0 for r in rcs) else 1
                print(f"[launch] workers done during hold rcs={rcs}",
                      flush=True)
                shutdown(code=code)
            time.sleep(1)
            continue
        if status == ElasticStatus.RESTART or \
                (holding and status == ElasticStatus.NORMAL):
            holding = False
            # re-read the OBSERVED member count: the relaunch runs at
            # whatever np the cluster actually gives back right now,
            # not the snapshot the watch() poll happened to see
            observed = elastic.wait_for_np()
            if not observed:
                print(f"[launch] elastic: membership changed but only "
                      f"{int(observed)} member(s) observed; holding",
                      flush=True)
                holding = True
                hold_since = time.time()
                time.sleep(1)
                continue
            old_world = active_world
            membership = _elastic_membership(elastic, args)
            if membership["node_index"] is None:
                # capped out by max_np: stand by until a slot opens
                print("[launch] elastic: beyond max_np, standing by",
                      flush=True)
                stop_workers()
                holding = True
                hold_since = time.time()
                time.sleep(1)
                continue
            new_world = membership["n_nodes"] * args.nproc_per_node
            print(f"[launch] elastic membership changed → relaunch as "
                  f"node {membership['node_index']} of "
                  f"{membership['n_nodes']} (observed np="
                  f"{int(observed)}): {membership['endpoints']}",
                  flush=True)
            stop_workers()
            policy.reset_all()           # fresh budget for the new epoch
            if args.ckpt_root and old_world != new_world:
                # the relaunch resumes at a DIFFERENT world size: the
                # workers will reshard the newest committed manifest
                # step onto the new mesh.  Same-size membership churn
                # (node replaced, quorum dip-and-recover) still resumes
                # but is not a reshard — booking it would make the
                # event/counter useless for alerting.
                _note_reshard(old_world, new_world, args.ckpt_root)
            active_world = new_world
            for i in range(args.nproc_per_node):
                start(i)

        alive = 0
        now = time.time()
        for i in policy.pop_due(now):    # backoff elapsed: relaunch
            start(i)
        for i, p in list(procs.items()):
            if policy.is_pending(i):
                alive += 1               # restart-pending counts as live
                continue
            ret = p.poll()
            if ret is None:
                alive += 1
            elif ret != 0:
                if policy.on_exit(i, ret, now,
                                  f"worker {i}") == "give_up":
                    shutdown(code=1)
                alive += 1
        if alive == 0:
            break
        time.sleep(1)
    if elastic is not None:
        elastic.stop()
    print("[launch] all workers finished", flush=True)


if __name__ == "__main__":
    main()
