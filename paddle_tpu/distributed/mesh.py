"""Auto-parallel mesh + placement API (reference:
python/paddle/distributed/auto_parallel/ — ProcessMesh, shard_tensor,
Placement(Shard/Replicate/Partial), completion/partition/reshard).

TPU-native: this maps 1:1 onto GSPMD.  ``ProcessMesh`` wraps
``jax.sharding.Mesh``; ``shard_tensor`` attaches a ``NamedSharding``; the
reference's completion/partition/reshard passes are XLA's SPMD partitioner
— we only annotate.  ``dtensor_from_fn``/``reshard`` are thin wrappers over
``jax.device_put`` with a new sharding.
"""
import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "auto_mesh",
           "shard_tensor", "shard_op", "reshard", "Shard", "Replicate",
           "Partial", "dtensor_from_fn"]

_GLOBAL_MESH = [None]


class Shard:
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate:
    def __repr__(self):
        return "Replicate()"

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return True

    def is_partial(self):
        return False


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return True


class ProcessMesh:
    """N-D logical mesh over devices.

    ``mesh``: nested list of process/device ids (reference layout) or a
    shape tuple; ``dim_names``: axis names (dp/mp/pp/...).
    """

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self._ids = arr
        self._shape = tuple(arr.shape)
        self._dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        devices = jax.devices()
        dev_arr = np.asarray([devices[i % len(devices)]
                              for i in arr.reshape(-1)],
                             dtype=object).reshape(arr.shape)
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    @property
    def jax_mesh(self):
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, pid):
        idx = np.argwhere(self._ids == pid)
        if idx.size == 0:
            return -1
        return int(idx[0][self._dim_names.index(dim)])

    def __enter__(self):
        self._prev = _GLOBAL_MESH[0]
        _GLOBAL_MESH[0] = self
        return self

    def __exit__(self, *exc):
        _GLOBAL_MESH[0] = self._prev
        return False

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                self._shape == other._shape and
                self._dim_names == other._dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


def set_mesh(mesh):
    _GLOBAL_MESH[0] = mesh


def get_mesh():
    return _GLOBAL_MESH[0]


def auto_mesh(dim_names=("dp",), shape=None):
    """Build a mesh over all visible devices with the given axis names."""
    n = jax.device_count()
    if shape is None:
        shape = (n,) + (1,) * (len(dim_names) - 1)
    return ProcessMesh(shape=shape, dim_names=dim_names)


def _placements_to_spec(placements, ndim):
    spec = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            spec[pl.dim] = mesh_dim  # temp: mesh axis index
    return spec


def shard_tensor(data, mesh, placements, dtype=None, stop_gradient=None):
    """Place a tensor on the mesh with the given per-mesh-axis placements.

    Returns a Tensor whose jax.Array carries the NamedSharding — XLA's SPMD
    partitioner (the reference's Partitioner+Reshard passes) takes over
    from there.
    """
    t = data if isinstance(data, Tensor) else Tensor(data)
    ndim = t.ndim
    axis_names = mesh.dim_names
    spec = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            cur = spec[pl.dim]
            if cur is None:
                spec[pl.dim] = axis_names[mesh_dim]
            elif isinstance(cur, tuple):
                spec[pl.dim] = cur + (axis_names[mesh_dim],)
            else:
                spec[pl.dim] = (cur, axis_names[mesh_dim])
    ns = NamedSharding(mesh.jax_mesh, P(*spec))
    val = jax.device_put(t._value, ns)
    out = Tensor(val, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient, name=t.name)
    out.process_mesh = mesh
    out.placements = list(placements)
    if getattr(t, "is_parameter", False):
        out.is_parameter = True
    return out


def reshard(x, mesh, placements):
    return shard_tensor(x, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_op(op, mesh=None, in_placements=None, out_placements=None):
    """Annotate an op's outputs with shardings (semi-auto).  With GSPMD the
    input annotations propagate, so this is mostly an assertion point."""
    def wrapper(*args, **kwargs):
        out = op(*args, **kwargs)
        if out_placements is not None and mesh is not None:
            if isinstance(out, Tensor):
                return shard_tensor(out, mesh, out_placements)
        return out
    return wrapper


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """reference: paddle.distributed.shard_layer — convert a Layer's
    parameters to distributed tensors on ``process_mesh``.

    ``shard_fn(name, layer, process_mesh)`` shards one sublayer's params
    in place; default replicates every parameter.  ``input_fn``/
    ``output_fn`` wrap forward to reshard activations at the boundary.
    """
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in list(sublayer._parameters.items()):
                if p is not None:
                    sublayer._parameters[pname] = shard_tensor(
                        p, mesh, [Replicate()] * p.ndim)

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)

    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lay, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lay, inputs, outputs: output_fn(outputs, process_mesh))
    return layer
