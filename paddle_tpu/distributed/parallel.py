"""DataParallel (reference: python/paddle/fluid/dygraph/parallel.py +
C++ imperative::Reducer gradient bucketing).

TPU-native: data parallelism is a sharding, not a wrapper protocol — the
compiled train step sees batch-sharded inputs and replicated params, and
XLA inserts the gradient all-reduce (bucketing/overlap done by the
latency-hiding scheduler, which is the Reducer's job in the reference).
This class keeps the reference's wrapper API: under a jitted step it simply
marks the model so hapi/engine shard the batch axis; in eager multi-process
mode it averages grads across processes after backward (no_sync supported).
"""
from contextlib import contextmanager

import jax

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from .env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._sync = True
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self.is_data_parallel = True
        if jax.device_count() > 1:
            from .engine import make_data_parallel_plan
            from .grad_comm import GradCommConfig
            # strategy may carry grad_comm knobs (bucketed/quantized
            # explicit reduce, or the zero1 plan flag); plain DP when not
            self._placement_plan = make_data_parallel_plan(
                grad_comm=GradCommConfig.from_strategy(strategy))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextmanager
    def no_sync(self):
        self._sync = False
        try:
            yield
        finally:
            self._sync = True

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Average grads across processes (multi-host eager path).  In the
        compiled/pjit path this is a no-op — GSPMD already reduced."""
        if not self._sync or get_world_size() <= 1:
            return
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            for p in self._layers.parameters():
                if p._grad is not None:
                    g = multihost_utils.process_allgather(p._grad)
                    p._grad = g.mean(axis=0)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    # delegate attribute access to the wrapped module (paddle behavior)
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


_SPLIT_CACHE = {}


def split(x, size, operation="linear", axis=0, num_partitions=None,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """reference: paddle.distributed.split — megatron-style sharded
    linear/embedding as a functional op.  Delegates to the fleet TP
    layers (Column/Row-parallel linear, VocabParallel embedding), cached
    per ``name`` so repeated calls reuse the distributed weights (the
    reference's unique_name behavior).  axis=1 splits the linear's
    output columns (column parallel); axis=0 splits rows (row parallel).
    """
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    # no name -> fresh distributed weights on every call (the
    # reference's unique_name behavior); pass name= to reuse weights
    # across steps
    key = name
    layer = _SPLIT_CACHE.get(key) if key is not None else None
    if layer is None:
        if operation == "linear":
            in_f, out_f = size
            if axis == 1:
                layer = ColumnParallelLinear(
                    in_f, out_f, weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
            else:
                layer = RowParallelLinear(
                    in_f, out_f, weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    input_is_parallel=False)
        elif operation == "embedding":
            num_emb, emb_dim = size
            layer = VocabParallelEmbedding(num_emb, emb_dim,
                                           weight_attr=weight_attr)
        else:
            raise ValueError(f"split: unknown operation {operation!r}")
        if key is not None:
            _SPLIT_CACHE[key] = layer
    return layer(x)
