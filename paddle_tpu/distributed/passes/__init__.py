"""paddle.distributed.passes (reference:
python/paddle/distributed/passes/ — graph passes applied to the static
program: fuse_optimizer, fuse_all_reduce, recompute, AMP, sharding...).

TPU-native: XLA owns operator fusion/scheduling and GSPMD owns
communication placement, so most reference passes have no separate
artifact to rewrite — their INTENT maps onto DistributedStrategy knobs
(recompute/amp/sharding meta-optimizers) or is already the compiler's
default (fusion).  ``new_pass`` returns a PassBase that records its
config; ``apply`` validates the mapping and is otherwise a no-op, so
reference pass-driving code runs unchanged.
"""

__all__ = ["new_pass", "PassBase", "PassManager"]

# reference pass name -> where the equivalent lives here
_KNOWN = {
    "fuse_optimizer": "XLA fuses the optimizer update chain at compile",
    "fuse_all_reduce": "GSPMD/XLA coalesce collectives",
    "fuse_gemm_epilogue": "XLA fuses bias/activation epilogues",
    "fuse_bn_act": "XLA fusion",
    "fuse_elewise_add_act": "XLA fusion",
    "auto_parallel_recompute": "fleet.utils.recompute / strategy",
    "auto_parallel_amp": "paddle.amp / DistributedStrategy.amp",
    "auto_parallel_fp16": "paddle.amp O2",
    "auto_parallel_sharding": "DistributedStrategy.sharding",
    "auto_parallel_gradient_merge": "GradientMerge meta-optimizer",
    "pipeline_scheduler_1F1B": "fleet pipeline stepper (1F1B)",
    "pipeline_scheduler_FThenB": "fleet pipeline stepper",
}


class PassBase:
    def __init__(self, name, attrs=None):
        if name not in _KNOWN:
            raise ValueError(
                f"unknown pass {name!r}; known passes: "
                f"{sorted(_KNOWN)}")
        self.name = name
        self.attrs = dict(attrs or {})

    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    def apply(self, main_programs=None, startup_programs=None,
              context=None):
        """No separate graph artifact to rewrite on TPU — see module
        docstring; returns the mapping note for introspection."""
        return _KNOWN[self.name]


def new_pass(name, pass_attrs=None):
    return PassBase(name, pass_attrs)


class PassManager:
    def __init__(self, passes=None):
        self.passes = list(passes or [])

    def append(self, p):
        self.passes.append(p)

    def apply(self, main_programs=None, startup_programs=None):
        return [p.apply(main_programs, startup_programs)
                for p in self.passes]
