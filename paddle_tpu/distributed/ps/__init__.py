"""Parameter-server mode for sparse/recsys models (reference:
paddle/fluid/distributed/ps/{table,service}/ — brpc services over
MemorySparseTable/dense tables with accessors, plus the Python runtime
python/paddle/distributed/fleet/runtime/the_one_ps.py).

TPU-native re-design, not a port: the dense training path on TPU is the
compiled SPMD program (no PS involved); what the PS class of models needs
is the *sparse* side — embedding tables far larger than HBM, touched by a
few thousand rows per step.  So this module is a lean CPU-side key-value
parameter service:

- ``SparseTable``: hash-map id → row (created on first touch by an
  initializer), updated server-side by an accessor rule (sgd / adagrad /
  "sum" for geo-async deltas) — the MemorySparseTable + accessor pair.
- ``DenseTable``: a flat array with the same push/pull protocol.
- ``PSServer``: threaded TCP service hosting tables; length-prefixed
  pickled frames (the in-repo store/rpc wire pattern; brpc's role).
- ``PSClient``: shards keys across N servers by ``id % n`` (the
  reference's key-shard layout), gathers pulls / scatters pushes.
- ``GeoSparseTable`` (client-side): local cache + accumulated deltas,
  flushed every ``geo_step`` pushes — geo-async SGD semantics.

Workers pull rows into the jax program's inputs, compute grads under the
normal autograd, and push sparse grads back; the TPU never holds the full
table.

Security: wire frames are pickled, but deserialization goes through a
RESTRICTED unpickler that admits only numpy arrays/scalars/dtypes and
builtin containers — a frame referencing any other global (e.g.
``os.system``) is rejected before construction, so a reachable port is
not an arbitrary-code-execution hole.  There is still no authentication
or encryption: run the PS on a trusted network segment (localhost /
cluster-private VLAN), exactly like the reference's brpc endpoints.

Scale envelope (deliberate lean design vs the reference's ~120k-LoC
brpc subsystem): tables are in-process Python dicts guarded by ONE lock
per table, rows travel fully pickled per request, and there is no SSD
tier, TTL eviction, or CTR accessor.  Good for O(10^6) rows and a few
thousand touched rows/step per shard; shard count is the scaling knob.
"""
import io
import pickle
import socket
import socketserver
import struct
import threading

import numpy as np

__all__ = ["SparseTable", "DenseTable", "PSServer", "PSClient",
           "GeoSparseTable"]


# ---------------------------------------------------------------------------
# tables (server side)
# ---------------------------------------------------------------------------

class _Accessor:
    """Server-side update rule (reference: accessors, e.g. sparse SGD /
    adagrad rules in paddle/fluid/distributed/ps/table/)."""

    def __init__(self, rule="sgd", lr=0.01, eps=1e-8):
        if rule not in ("sgd", "adagrad", "sum"):
            raise ValueError(f"unknown accessor rule {rule!r}")
        self.rule = rule
        self.lr = lr
        self.eps = eps

    def init_state(self, dim):
        return np.zeros(dim, np.float32) if self.rule == "adagrad" else None

    def apply(self, row, grad, state):
        if self.rule == "sgd":
            row -= self.lr * grad
        elif self.rule == "adagrad":
            state += grad * grad
            row -= self.lr * grad / (np.sqrt(state) + self.eps)
        else:                     # "sum": geo-async delta accumulation
            row += grad
        return row, state


class SparseTable:
    """id → row table; rows materialize on first pull (initializer)."""

    def __init__(self, dim, initializer=None, rule="sgd", lr=0.01,
                 seed=0):
        self.dim = dim
        self.rows = {}
        self.states = {}
        self.accessor = _Accessor(rule, lr)
        self._rng = np.random.RandomState(seed)
        self._init = initializer or (
            lambda rng, dim: (rng.uniform(-0.05, 0.05, dim)
                              .astype(np.float32)))
        self.lock = threading.Lock()

    def _row(self, i):
        i = int(i)
        r = self.rows.get(i)
        if r is None:
            r = self._init(self._rng, self.dim)
            self.rows[i] = r
            self.states[i] = self.accessor.init_state(self.dim)
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(i) for i in ids]) if len(ids) \
                else np.zeros((0, self.dim), np.float32)

    def push(self, ids, grads):
        with self.lock:
            for i, g in zip(ids, np.asarray(grads, np.float32)):
                i = int(i)
                row = self._row(i)
                new_row, st = self.accessor.apply(row, g,
                                                  self.states.get(i))
                self.rows[i] = new_row
                self.states[i] = st

    def state(self):
        with self.lock:
            return {"dim": self.dim, "rows": dict(self.rows)}

    def load(self, snap):
        with self.lock:
            self.rows = {int(k): np.asarray(v, np.float32)
                         for k, v in snap["rows"].items()}


class DenseTable:
    """Flat parameter block with the same push/pull protocol."""

    def __init__(self, shape, init=None, rule="sgd", lr=0.01):
        self.value = (np.zeros(shape, np.float32) if init is None
                      else np.asarray(init, np.float32).copy())
        self.accessor = _Accessor(rule, lr)
        self._state = self.accessor.init_state(self.value.shape)
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            return self.value.copy()

    def push(self, grad):
        with self.lock:
            self.value, self._state = self.accessor.apply(
                self.value, np.asarray(grad, np.float32), self._state)

    def state(self):
        with self.lock:
            return {"value": self.value.copy()}

    def load(self, snap):
        with self.lock:
            self.value = np.asarray(snap["value"], np.float32).copy()


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

class _RestrictedUnpickler(pickle.Unpickler):
    """Admit only the globals a PS frame legitimately needs: numpy array
    reconstruction + dtypes.  Everything else (os.system, subprocess,
    functools, ...) raises before any object is constructed."""

    _ALLOWED = {
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.numeric", "_frombuffer"),
        ("numpy._core.numeric", "_frombuffer"),
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED or \
                module in ("numpy.dtypes", "numpy._core.numerictypes",
                           "numpy.core.numerictypes"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"PS wire: refusing to unpickle global {module}.{name} "
            "(only numpy arrays and builtin containers are accepted)")


def _safe_loads(data):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _send_frame(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_frame(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return _safe_loads(bytes(buf))


class PSServer:
    """One PS shard: hosts tables, serves pull/push/save/load/stop."""

    def __init__(self, port=0, host="127.0.0.1"):
        self.tables = {}
        srv_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        try:
                            req = _recv_frame(self.request)
                        except (ConnectionError, OSError):
                            return
                        except Exception as e:
                            # malicious/garbage/truncated frame (rejected
                            # unpickle, EOFError, ...): report + drop conn
                            _send_frame(self.request,
                                        {"ok": False, "error": repr(e)})
                            return
                        _send_frame(self.request, srv_self._dispatch(req))
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    # table management happens locally (the launcher creates tables on
    # every shard with the same spec) or via the "create" op
    def create_sparse_table(self, name, dim, **kw):
        self.tables[name] = SparseTable(dim, **kw)

    def create_dense_table(self, name, shape, **kw):
        self.tables[name] = DenseTable(shape, **kw)

    def _dispatch(self, req):
        try:
            op = req["op"]
            if op == "create_sparse":
                self.create_sparse_table(req["name"], req["dim"],
                                         **req.get("kw", {}))
                return {"ok": True}
            if op == "create_dense":
                self.create_dense_table(req["name"], req["shape"],
                                        **req.get("kw", {}))
                return {"ok": True}
            if op == "pull_sparse":
                return {"ok": True,
                        "rows": self.tables[req["name"]].pull(req["ids"])}
            if op == "push_sparse":
                self.tables[req["name"]].push(req["ids"], req["grads"])
                return {"ok": True}
            if op == "pull_dense":
                return {"ok": True,
                        "value": self.tables[req["name"]].pull()}
            if op == "push_dense":
                self.tables[req["name"]].push(req["grad"])
                return {"ok": True}
            if op == "save":
                return {"ok": True,
                        "state": {n: t.state()
                                  for n, t in self.tables.items()}}
            if op == "load":
                for n, snap in req["state"].items():
                    self.tables[n].load(snap)
                return {"ok": True}
            if op == "ping":
                return {"ok": True}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except Exception as e:   # surface to the client, keep serving
            return {"ok": False, "error": repr(e)}

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _Conn:
    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=60)
        self.lock = threading.Lock()

    def call(self, req):
        with self.lock:
            _send_frame(self.sock, req)
            resp = _recv_frame(self.sock)
        if not resp.get("ok"):
            raise RuntimeError(f"PS error: {resp.get('error')}")
        return resp

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class PSClient:
    """Worker-side handle; keys shard across servers by ``id % n``."""

    def __init__(self, endpoints):
        self.conns = []
        for ep in endpoints:
            host, _, port = ep.partition(":")
            self.conns.append(_Conn(host or "127.0.0.1", int(port)))
        self.n = len(self.conns)

    # -- table creation (broadcast to every shard) --------------------------
    def create_sparse_table(self, name, dim, **kw):
        for c in self.conns:
            c.call({"op": "create_sparse", "name": name, "dim": dim,
                    "kw": kw})

    def create_dense_table(self, name, shape, **kw):
        # dense lives on shard 0 only (small); sparse is what scales
        self.conns[0].call({"op": "create_dense", "name": name,
                            "shape": shape, "kw": kw})

    # -- sparse -------------------------------------------------------------
    def _shard_ids(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        shard = ids % self.n
        order = []
        per = []
        for s in range(self.n):
            idx = np.nonzero(shard == s)[0]
            order.append(idx)
            per.append(ids[idx])
        return ids, order, per

    def pull_sparse(self, name, ids):
        ids_flat, order, per = self._shard_ids(ids)
        dim = None
        out = None
        for s, (idx, sid) in enumerate(zip(order, per)):
            if len(sid) == 0:
                continue
            rows = self.conns[s].call(
                {"op": "pull_sparse", "name": name,
                 "ids": sid.tolist()})["rows"]
            if out is None:
                dim = rows.shape[1] if rows.ndim == 2 else 0
                out = np.zeros((len(ids_flat), dim), np.float32)
            out[idx] = rows
        if out is None:
            raise ValueError("pull_sparse with no ids")
        return out.reshape(*np.shape(ids), dim)

    def push_sparse(self, name, ids, grads):
        ids_flat, order, per = self._shard_ids(ids)
        grads = np.asarray(grads, np.float32).reshape(len(ids_flat), -1)
        for s, (idx, sid) in enumerate(zip(order, per)):
            if len(sid) == 0:
                continue
            self.conns[s].call(
                {"op": "push_sparse", "name": name, "ids": sid.tolist(),
                 "grads": grads[idx]})

    # -- dense --------------------------------------------------------------
    def pull_dense(self, name):
        return self.conns[0].call({"op": "pull_dense",
                                   "name": name})["value"]

    def push_dense(self, name, grad):
        self.conns[0].call({"op": "push_dense", "name": name,
                            "grad": np.asarray(grad, np.float32)})

    # -- persistence ---------------------------------------------------------
    def save_persistables(self, path):
        """Snapshot every shard's tables to ``path`` (one file per shard)."""
        import os
        os.makedirs(path, exist_ok=True)
        for s, c in enumerate(self.conns):
            state = c.call({"op": "save"})["state"]
            with open(os.path.join(path, f"ps_shard_{s}.pkl"), "wb") as f:
                pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)

    def load_persistables(self, path):
        import os
        for s, c in enumerate(self.conns):
            fp = os.path.join(path, f"ps_shard_{s}.pkl")
            with open(fp, "rb") as f:
                state = _safe_loads(f.read())
            c.call({"op": "load", "state": state})

    def close(self):
        for c in self.conns:
            c.close()


# ---------------------------------------------------------------------------
# geo-async (client-side cache + delta accumulation)
# ---------------------------------------------------------------------------

class GeoSparseTable:
    """Geo-async SGD view of a sparse table (reference: geo-async mode —
    workers train on a local replica and ship accumulated DELTAS every
    ``geo_step`` updates; the server's accessor rule for the table must
    be "sum" so deltas add).

    Local updates apply immediately (plain SGD on the cache) so the
    worker trains on fresh values; ``flush()``/auto-flush pushes the
    accumulated difference and re-pulls the merged rows.
    """

    def __init__(self, client, name, lr=0.01, geo_step=8):
        self.client = client
        self.name = name
        self.lr = lr
        self.geo_step = geo_step
        self.cache = {}
        self.delta = {}
        self._pushes = 0

    def pull(self, ids):
        ids = np.asarray(ids, np.int64).ravel()
        missing = [i for i in ids.tolist() if i not in self.cache]
        if missing:
            rows = self.client.pull_sparse(self.name, missing)
            for i, r in zip(missing, rows):
                self.cache[int(i)] = r.astype(np.float32).copy()
        return np.stack([self.cache[int(i)] for i in ids])

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        for i, g in zip(ids.tolist(), grads):
            upd = -self.lr * g
            self.cache[i] = self.cache[i] + upd
            self.delta[i] = self.delta.get(i, 0.0) + upd
        self._pushes += 1
        if self._pushes >= self.geo_step:
            self.flush()

    def flush(self):
        if self.delta:
            ids = list(self.delta.keys())
            deltas = np.stack([self.delta[i] for i in ids])
            # server table rule must be "sum": the delta adds into the row
            self.client.push_sparse(self.name, ids, deltas)
            rows = self.client.pull_sparse(self.name, ids)
            for i, r in zip(ids, rows):
                self.cache[int(i)] = r.astype(np.float32).copy()
            self.delta.clear()
        self._pushes = 0
