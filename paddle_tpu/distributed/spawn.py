"""paddle.distributed.spawn compat (reference:
python/paddle/distributed/spawn.py).

On TPU a single process drives all local chips (SPMD), so nprocs>1 process
forking is only meaningful for CPU tests; we emulate by running the
function once with the full device set visible — parallelism comes from
sharding, not processes.  True multi-host launch is the `launch` CLI.
"""
import os


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    # Emulated: single driver process, devices provide the parallelism.
    os.environ.setdefault("PADDLE_TRAINERS_NUM", "1")
    func(*args)
    return None
