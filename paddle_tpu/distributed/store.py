"""TCPStore — rank-0-hosted key-value rendezvous store (reference:
paddle/fluid/distributed/store/tcp_store.cc, exposed to Python as
``paddle.distributed.TCPStore``-alike via pybind).

Backed by the native C++ server/client in paddle_tpu/csrc/tcp_store.cc
(one connection-handler thread per worker, condition-variable-blocked
GET/WAIT).  A pure-Python implementation of the same wire protocol is the
fallback so behavior is identical without the toolchain.

On TPU the PJRT coordination service (jax.distributed) replaces NCCL
unique-id exchange; the store remains the framework's control plane for
barriers, elastic membership, and launcher rendezvous.
"""
import ctypes
import functools
import os
import socket
import socketserver
import struct
import threading
import time

from .. import observability as _obs
from ..framework import failpoints as _fp
from ..framework import native
from ..framework.retry import RetryPolicy

__all__ = ["TCPStore", "MasterStore"]

_SET, _GET, _ADD, _WAIT, _DEL, _NUMKEYS = 1, 2, 3, 4, 5, 6

# failpoint sites (see framework/failpoints.py; armed via
# PADDLE_FAILPOINTS="store.get=error*2;..." or set_failpoint).
# store.<op> sites fire in the TCPStore facade — the CALLER sees the
# fault (elastic watch flap tests).  store.connect and store.io fire
# INSIDE the Python client's retry envelope, so those faults are
# retried like real network errors.
_FP_CONNECT = _fp.register("store.connect")
_FP_IO = _fp.register("store.io")
_FP_SET = _fp.register("store.set")
_FP_GET = _fp.register("store.get")
_FP_ADD = _fp.register("store.add")
_FP_WAIT = _fp.register("store.wait")

# retry envelope for the Python client: reconnect attempts back off
# exponentially with jitter up to the cap between tries, bounded
# overall by the store timeout (the "deadline").  The sleep/expiry
# mechanics live in the shared framework.retry policy (ISSUE 16); the
# loop semantics — what retries, what surfaces, the mid-ADD
# at-most-once rule — stay in the client below, where they are the
# wire contract.  Every backoff = one retry about to happen; the
# counter makes flapping visible without log archaeology.
_RETRY = RetryPolicy(base=0.05, cap=2.0,
                     on_retry=lambda: _obs.inc("pt_store_retries_total"))


class _PyStoreServer:
    """Python fallback server speaking the native wire protocol."""

    def __init__(self, port=0):
        kv = {}
        cond = threading.Condition()

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_mu:
                    outer._conns.add(sock)
                try:
                    self._serve(sock)
                finally:
                    with outer._conns_mu:
                        outer._conns.discard(sock)

            def _serve(self, sock):
                while True:
                    hdr = _recv_full(sock, 5)
                    if hdr is None:
                        return
                    op, keylen = struct.unpack("<BI", hdr)
                    key = _recv_full(sock, keylen) if keylen else b""
                    if key is None:
                        return
                    lenbuf = _recv_full(sock, 8)
                    if lenbuf is None:
                        return
                    (paylen,) = struct.unpack("<Q", lenbuf)
                    payload = _recv_full(sock, paylen) if paylen else b""
                    if payload is None:
                        return
                    status, out = 0, b""
                    if op == _SET:
                        with cond:
                            kv[key] = payload
                            cond.notify_all()
                    elif op in (_GET, _WAIT):
                        (timeout_ms,) = struct.unpack("<q", payload)
                        deadline = (None if timeout_ms < 0
                                    else time.monotonic() + timeout_ms / 1e3)
                        with cond:
                            while key not in kv and not outer._stopped:
                                rem = (None if deadline is None
                                       else deadline - time.monotonic())
                                if rem is not None and rem <= 0:
                                    break
                                cond.wait(rem)
                            if key in kv:
                                out = kv[key] if op == _GET else b""
                            else:
                                status = 1
                    elif op == _ADD:
                        (delta,) = struct.unpack("<q", payload)
                        with cond:
                            prev = kv.get(key, b"")
                            cur = (struct.unpack("<q", prev)[0]
                                   if len(prev) == 8 else 0) + delta
                            kv[key] = struct.pack("<q", cur)
                            out = kv[key]
                            cond.notify_all()
                    elif op == _DEL:
                        with cond:
                            status = 0 if kv.pop(key, None) is not None else 1
                    elif op == _NUMKEYS:
                        with cond:
                            out = struct.pack("<q", len(kv))
                    else:
                        status = 1
                    try:
                        sock.sendall(struct.pack("<BQ", status, len(out)) + out)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._stopped = False
        self._cond = cond
        self._conns = set()
        self._conns_mu = threading.Lock()
        self._server = Server(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._stopped = True
        with self._cond:  # wake handlers parked in infinite GET/WAIT
            self._cond.notify_all()
        self._server.shutdown()
        self._server.server_close()
        # sever live connections so clients see a dead server (EOF/RST)
        # instead of being silently served by zombie handler threads — a
        # stopped server must look stopped, or restart/reconnect logic
        # can never be exercised honestly
        with self._conns_mu:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


def _recv_full(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _PyStoreClient:
    """Wire-protocol client with resilience: connect (and reconnect after
    a lost peer) retries with exponential backoff + jitter under an
    overall per-call deadline, and each request is retried over a fresh
    connection when the socket dies mid-flight.

    Idempotent ops (SET/GET/WAIT/DEL/NUMKEYS) are at-least-once — a
    replayed SET is harmless.  ADD is at-most-once: once any request
    bytes may have reached the server, a failure raises instead of
    retrying, because a double-applied ADD would skip counter values and
    strand ``barrier()`` waiters on a release epoch nobody sets.  An ADD
    that fails before the first byte (connect refused, injected
    store.connect/store.io fault) is still retried safely.
    """

    def __init__(self, host, port, timeout_ms):
        self._host, self._port = host, port
        self._timeout_s = (timeout_ms / 1e3 if timeout_ms is not None
                           and timeout_ms >= 0 else 30.0)
        self._sock = None
        self._closed = False
        self._mu = threading.Lock()
        self._connect(time.monotonic() + self._timeout_s)

    def _connect_once(self):
        """One connection attempt (no retry — callers own the backoff)."""
        if _fp._ACTIVE:
            _fp.fire(_FP_CONNECT)
        sock = socket.create_connection(
            (self._host, self._port), timeout=5)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        return sock

    def _connect(self, deadline):
        """Initial connect: retry with backoff until deadline."""
        attempt = 0
        while True:
            if self._closed:   # outside the try: must not be retried
                raise ConnectionError("TCPStore client is closed")
            try:
                return self._connect_once()
            except OSError as e:
                if _RETRY.expired(deadline):
                    raise TimeoutError(
                        f"TCPStore: cannot reach {self._host}:{self._port} "
                        f"within {self._timeout_s:.1f}s "
                        f"(last error: {e})") from e
                _RETRY.backoff(attempt, deadline)
                attempt += 1

    def _close_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def request(self, op, key, payload, op_timeout_s=0.0, budget_s=None):
        """``op_timeout_s``: how long the server may legitimately park
        this op (GET/WAIT); the retry deadline must outlast it or a flap
        late in the park window would get zero retries.  ``None`` means
        the op waits indefinitely server-side — the client then waits
        (and retries) indefinitely too, matching the native client.

        ``self._mu`` serializes socket use.  It is NOT held across the
        backoff sleeps between attempts, so a flap-stalled op cannot
        head-of-line-block other threads for the whole retry budget —
        but it IS held while a GET/WAIT is parked server-side (one
        socket, one in-flight request).  Threads sharing a client should
        keep their blocking waits short (the framework's own probes use
        ~1s); give long barrier-style waits their own TCPStore.

        ``budget_s`` overrides the client's retry budget for this call
        (shutdown paths that must fail fast, e.g. the elastic tombstone).

        Replay caveat: retried ops are at-least-once, and while SET/GET/
        WAIT results are replay-stable, a DEL whose first attempt was
        applied but whose reply was lost reports "not found" on replay —
        treat delete_key()'s return value as best-effort."""
        # delta-0 ADD is a pure read (the elastic seq probe): replaying
        # it cannot double-count, so it keeps the idempotent retry path
        idempotent = op != _ADD or payload == struct.pack("<q", 0)
        extra = (float("inf") if op_timeout_s is None
                 else max(op_timeout_s, 0))
        base_budget = self._timeout_s if budget_s is None else budget_s
        deadline = time.monotonic() + base_budget + extra
        attempt = 0
        while True:
            if self._closed:   # outside the try: must not be retried
                raise ConnectionError("TCPStore client is closed")
            risky = False      # True once request bytes may be out
            connecting = False
            sock = None
            try:
                with self._mu:
                    # local ref: a concurrent close() nulls self._sock,
                    # and None.sendall would escape the OSError retry net
                    sock = self._sock
                    if sock is None:
                        connecting = True
                        sock = self._connect_once()
                        connecting = False
                    # bound the blocking send/recv by the remaining
                    # deadline: a half-open peer (power loss, partition
                    # with no FIN/RST) must surface as a timeout, not
                    # hang this call forever
                    rem = deadline - time.monotonic()
                    sock.settimeout(None if rem == float("inf")
                                    else max(0.5, rem))
                    if _fp._ACTIVE:
                        _fp.fire(_FP_IO)   # in-envelope fault: retried
                    msg = struct.pack("<BI", op, len(key)) + key + \
                        struct.pack("<Q", len(payload)) + payload
                    risky = True
                    sock.sendall(msg)
                    hdr = _recv_full(sock, 9)
                    if hdr is None:
                        raise ConnectionError("TCPStore connection lost")
                    status, outlen = struct.unpack("<BQ", hdr)
                    out = _recv_full(sock, outlen) if outlen else b""
                    if out is None:   # connection died mid-body
                        raise ConnectionError("TCPStore connection lost")
                    return status, out
            except OSError as e:  # incl. Connection/TimeoutError
                with self._mu:
                    # close only the socket that failed: another thread
                    # may have already reconnected self._sock to a
                    # healthy replacement while we waited for the lock
                    if self._sock is sock:
                        self._close_sock()
                    elif sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                if not idempotent and risky:
                    # the server may or may not have applied the ADD;
                    # replaying could double-count — surface instead
                    raise ConnectionError(
                        "TCPStore: connection lost mid-ADD; the "
                        "increment may or may not have been applied "
                        f"({e})") from e
                if _RETRY.expired(deadline):
                    if connecting:
                        raise TimeoutError(
                            f"TCPStore: cannot reach "
                            f"{self._host}:{self._port} within the "
                            f"{base_budget + extra:.1f}s retry "
                            f"budget (last error: {e})") from e
                    raise ConnectionError(
                        f"TCPStore: request failed after its "
                        f"{base_budget + extra:.1f}s retry "
                        f"budget ({e})") from e
                _RETRY.backoff(attempt, deadline)
                attempt += 1

    def close(self):
        self._closed = True     # in-flight retries turn into clean errors
        self._close_sock()


def _timed_op(name):
    """Telemetry wrapper for the store facade ops: per-op count + wall
    latency (``pt_store_*``), covering the whole connect/retry envelope
    — errors and timeouts are recorded too, since a slow failure is the
    sample an operator needs."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            if not _obs.enabled():
                return fn(self, *args, **kwargs)
            t0 = time.perf_counter()
            try:
                return fn(self, *args, **kwargs)
            finally:
                _obs.inc("pt_store_ops_total", op=name)
                _obs.observe("pt_store_op_latency_ms",
                             (time.perf_counter() - t0) * 1e3, op=name)
        return wrapper
    return deco


class TCPStore:
    """Distributed KV store.  ``is_master=True`` also hosts the server.

    API mirrors the reference: set/get/add/wait/delete_key, plus a
    counter-based ``barrier``.

    ``timeout`` doubles as the resilience deadline: connect, reconnect
    and per-op retry (Python client) give up once it lapses.
    ``use_native=False`` forces the pure-Python client/server even when
    the C++ library is available (tests, failpoint injection).
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30.0, use_native=None):
        if use_native is None:
            use_native = os.environ.get("PADDLE_STORE_NATIVE", "1") != "0"
        self._lib = native.get_lib() if use_native else None
        self._server = None
        self._server_h = None
        self.world_size = world_size
        timeout_ms = int(timeout * 1000)
        if is_master:
            if self._lib is not None:
                self._server_h = self._lib.pt_store_server_start(port)
                if not self._server_h:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = self._lib.pt_store_server_port(self._server_h)
            else:
                self._server = _PyStoreServer(port)
                port = self._server.port
            host = "127.0.0.1" if host in ("", "0.0.0.0") else host
        self.host, self.port = host, port
        if self._lib is not None:
            self._client = self._lib.pt_store_client_connect(
                host.encode(), port, timeout_ms)
            if not self._client:
                raise TimeoutError(f"TCPStore: cannot reach {host}:{port}")
        else:
            self._client = _PyStoreClient(host, port, timeout_ms)

    # -- core ops ---------------------------------------------------
    @_timed_op("set")
    def set(self, key, value, retry_budget=None):
        """``retry_budget`` (seconds, Python client only) caps this
        call's reconnect/retry envelope below the store timeout — for
        shutdown-path writes that must fail fast, not resiliently."""
        if _fp._ACTIVE:
            _fp.fire(_FP_SET)
        if isinstance(value, str):
            value = value.encode()
        if self._lib is not None:
            buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
                if value else None
            rc = self._lib.pt_store_set(self._client, key.encode(), buf,
                                        len(value))
            if rc != 0:
                raise ConnectionError("TCPStore set failed")
        else:
            self._client.request(_SET, key.encode(), value,
                                 budget_s=retry_budget)

    @_timed_op("get")
    def get(self, key, timeout=30.0):
        if _fp._ACTIVE:
            _fp.fire(_FP_GET)
        tmo = int(timeout * 1000) if timeout is not None else -1
        if self._lib is not None:
            import ctypes
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.pt_store_get(self._client, key.encode(), tmo,
                                       ctypes.byref(out))
            if n == -1:
                raise KeyError(key)
            if n < 0:
                raise ConnectionError("TCPStore get failed")
            return native.take_buffer(self._lib, out, n)
        status, out = self._client.request(
            _GET, key.encode(), struct.pack("<q", tmo),
            op_timeout_s=timeout)
        if status != 0:
            raise KeyError(key)
        return out

    @_timed_op("add")
    def add(self, key, delta=1):
        if _fp._ACTIVE:
            _fp.fire(_FP_ADD)
        if self._lib is not None:
            v = self._lib.pt_store_add(self._client, key.encode(), delta)
            if v == -(2 ** 63):
                raise ConnectionError("TCPStore add failed")
            return v
        status, out = self._client.request(
            _ADD, key.encode(), struct.pack("<q", delta))
        if status != 0 or len(out) != 8:
            raise ConnectionError("TCPStore add failed")
        return struct.unpack("<q", out)[0]

    @_timed_op("wait")
    def wait(self, keys, timeout=30.0):
        if _fp._ACTIVE:
            _fp.fire(_FP_WAIT)
        if isinstance(keys, str):
            keys = [keys]
        tmo = int(timeout * 1000) if timeout is not None else -1
        for key in keys:
            if self._lib is not None:
                rc = self._lib.pt_store_wait(self._client, key.encode(), tmo)
                if rc == 1:
                    raise TimeoutError(
                        f"TCPStore: wait({key!r}) expired after {timeout}s "
                        "without the key being set")
                if rc != 0:
                    raise ConnectionError("TCPStore wait failed")
            else:
                status, _ = self._client.request(
                    _WAIT, key.encode(), struct.pack("<q", tmo),
                    op_timeout_s=timeout)
                if status != 0:
                    # status byte 1 == server-side expiry (or the server
                    # shut down while we were parked on the key)
                    raise TimeoutError(
                        f"TCPStore: wait({key!r}) expired after {timeout}s "
                        "without the key being set")

    def delete_key(self, key):
        if self._lib is not None:
            return self._lib.pt_store_delete(self._client, key.encode()) == 0
        status, _ = self._client.request(_DEL, key.encode(), b"")
        return status == 0

    def num_keys(self):
        if self._lib is not None:
            return self._lib.pt_store_num_keys(self._client)
        _, out = self._client.request(_NUMKEYS, b"", b"")
        return struct.unpack("<q", out)[0]

    # -- composite --------------------------------------------------
    def barrier(self, name="barrier", world_size=None, timeout=60.0):
        """Counter barrier: every rank adds 1, then waits for the release
        key that the last arriver sets."""
        n = world_size or self.world_size
        arrived = self.add(f"__{name}/count", 1)
        epoch = (arrived - 1) // n
        release = f"__{name}/release/{epoch}"
        if arrived % n == 0:
            self.set(release, b"1")
        self.wait([release], timeout=timeout)

    def close(self):
        if self._lib is not None:
            if self._client:
                self._lib.pt_store_client_close(self._client)
                self._client = None
            if self._server_h:
                self._lib.pt_store_server_stop(self._server_h)
                self._server_h = None
        else:
            if self._client is not None:
                self._client.close()
                self._client = None
            if self._server is not None:
                self._server.stop()
                self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def MasterStore(world_size, timeout=30.0):
    """Build the store from launcher env (PADDLE_MASTER,
    PADDLE_TRAINER_ID), rank 0 hosting."""
    master = os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, _, port = master.partition(":")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    return TCPStore(host or "127.0.0.1", int(port or 0), is_master=rank == 0,
                    world_size=world_size, timeout=timeout)
