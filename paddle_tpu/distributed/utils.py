"""paddle.distributed.utils (reference: python/paddle/distributed/utils/
— log utils + the MoE global_scatter/global_gather all-to-all ops).

TPU-native mapping: the reference's ragged count-driven NCCL
all-to-alls are expressed as static-shape ``lax.all_to_all`` exchanges
over capacity-bucketed dispatch buffers — the implementation lives with
the MoE machinery (incubate/distributed/models/moe/utils.py) and is
re-exported here for the reference import path.
"""
import logging

from ..incubate.distributed.models.moe.utils import (  # noqa: F401
    global_scatter, global_gather)

__all__ = ["get_logger", "global_scatter", "global_gather"]


def get_logger(log_level=logging.INFO, name="paddle_tpu.distributed"):
    """reference: paddle.distributed.utils.log_utils.get_logger."""
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger
